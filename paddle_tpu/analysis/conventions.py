"""Framework convention lints: AST-level checks over the package source.

These join the metrics-naming lint (tests/test_metrics.py) as the
repo's self-auditing layer — run in tier-1 by
``tests/test_conventions.py`` and from ``tools/program_audit.py
--lint``. Each lint returns a list of human-readable violation strings
(empty = clean):

* :func:`lint_env_knob_parses` — no ``int()``/``float()`` of a
  ``PADDLE_TPU_*`` env read outside the shared helper
  (``paddle_tpu/utils/envparse.py``): a garbled knob must warn+default
  (or raise a NAMED error), never detonate as an anonymous ValueError
  mid-run.
* :func:`lint_env_knob_docs` — every ``PADDLE_TPU_*`` knob the package
  reads is documented in README.md.
* :func:`lint_fault_sites` — every ``fault.site("...")`` string is
  registered in ``fault.inject.KNOWN_SITES``/``DYNAMIC_SITES`` and every
  registered site still has a call site (no dead sites); the README
  fault-site table mirrors the registry.
* :func:`lint_threads` — every ``threading.Thread`` in the package is
  daemon (``daemon=True`` at construction or ``.daemon = True`` before
  start) or provably joined in its module: a silent non-daemon thread
  wedges interpreter shutdown on the exact runs (chaos kills, SIGTERM
  drains) this repo exists to survive.
* :func:`lint_event_kinds` — every literal kind emitted through
  ``profiler/events.py`` is declared (with a severity) in
  ``events.KIND_SEVERITY``, so ``tools/obs_tail.py`` renders it instead
  of dropping it as garbage.

The lints parse source with ``ast`` — nothing is imported or executed,
so they run anywhere CI does.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["package_root", "lint_env_knob_parses", "lint_env_knob_docs",
           "lint_fault_sites", "lint_threads", "lint_event_kinds",
           "collect_env_knobs", "run_all"]

_ENV_PREFIX = "PADDLE_TPU_"
_HELPER_SUFFIX = os.path.join("utils", "envparse.py")


def package_root() -> str:
    """The paddle_tpu package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _py_files(root: Optional[str] = None) -> Iterable[Tuple[str, str]]:
    root = root or package_root()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                yield path, os.path.relpath(path, root)


def _parse(path: str) -> Optional[ast.AST]:
    try:
        with open(path) as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None


# -- env knobs ---------------------------------------------------------------

def _env_read_names(node: ast.AST) -> List[str]:
    """PADDLE_TPU_* literals read from the environment inside `node`
    (os.environ.get / os.getenv / os.environ[...])."""
    out = []
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            is_get = isinstance(f, ast.Attribute) and f.attr == "get" \
                and isinstance(f.value, (ast.Attribute, ast.Name)) \
                and (getattr(f.value, "attr", None) == "environ"
                     or getattr(f.value, "id", None) in ("environ", "env"))
            is_getenv = (isinstance(f, ast.Attribute)
                         and f.attr == "getenv") or \
                (isinstance(f, ast.Name) and f.id == "getenv")
            if (is_get or is_getenv) and n.args and \
                    isinstance(n.args[0], ast.Constant) and \
                    isinstance(n.args[0].value, str) and \
                    n.args[0].value.startswith(_ENV_PREFIX):
                out.append(n.args[0].value)
        elif isinstance(n, ast.Subscript):
            base_ok = (getattr(n.value, "attr", None) == "environ"
                       or getattr(n.value, "id", None) in ("environ",
                                                           "env"))
            sl = n.slice
            if base_ok and isinstance(sl, ast.Constant) and \
                    isinstance(sl.value, str) and \
                    sl.value.startswith(_ENV_PREFIX):
                out.append(sl.value)
    return out


def lint_env_knob_parses(root: Optional[str] = None) -> List[str]:
    """int()/float() wrapped directly around a PADDLE_TPU_* env read,
    anywhere but the shared helper."""
    violations = []
    for path, rel in _py_files(root):
        if rel.endswith(_HELPER_SUFFIX):
            continue
        tree = _parse(path)
        if tree is None:
            continue
        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id in ("int", "float")):
                continue
            names = [x for a in n.args for x in _env_read_names(a)]
            if names:
                violations.append(
                    f"{rel}:{n.lineno}: {n.func.id}() of env knob(s) "
                    f"{sorted(set(names))} — use "
                    f"paddle_tpu.utils.envparse.env_{n.func.id} (garbled "
                    f"values must warn+default or raise a named error)")
    return violations


_HELPER_FNS = ("env_int", "env_float", "env_bool", "env_str")


def _helper_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to envparse helpers in this module — including
    renamed imports (`from ...envparse import env_int as _int_knob`),
    which would otherwise be invisible to the knob collection."""
    names = set(_HELPER_FNS)
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module and \
                n.module.endswith("envparse"):
            for a in n.names:
                if a.name in _HELPER_FNS:
                    names.add(a.asname or a.name)
    return names


def collect_env_knobs(root: Optional[str] = None) -> Dict[str, str]:
    """Every PADDLE_TPU_* knob the package reads -> one 'file:line'
    witness. Sources: direct environ reads, envparse helper calls
    (aliased imports included), and RetryPolicy.from_env(prefix)
    families."""
    knobs: Dict[str, str] = {}

    def note(name: str, rel: str, lineno: int):
        knobs.setdefault(name, f"{rel}:{lineno}")

    for path, rel in _py_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        helper_names = _helper_aliases(tree)
        for n in ast.walk(tree):
            if not isinstance(n, (ast.Call, ast.Subscript)):
                continue
            for name in _env_read_names(n):
                note(name, rel, n.lineno)
            if not isinstance(n, ast.Call):
                continue
            fname = getattr(n.func, "id", getattr(n.func, "attr", ""))
            if fname in helper_names and n.args and \
                    isinstance(n.args[0], ast.Constant) and \
                    isinstance(n.args[0].value, str) and \
                    n.args[0].value.startswith(_ENV_PREFIX):
                note(n.args[0].value, rel, n.lineno)
            if fname == "from_env" and n.args and \
                    isinstance(n.args[0], ast.Constant) and \
                    isinstance(n.args[0].value, str):
                prefix = n.args[0].value.upper()
                for suffix in ("RETRIES", "BACKOFF", "TIMEOUT"):
                    note(f"{_ENV_PREFIX}{prefix}_{suffix}", rel, n.lineno)
    return knobs


def lint_env_knob_docs(readme_path: Optional[str] = None,
                       root: Optional[str] = None) -> List[str]:
    """Every knob the package reads must appear in README.md."""
    if readme_path is None:
        readme_path = os.path.join(os.path.dirname(package_root()),
                                   "README.md")
    try:
        with open(readme_path) as f:
            readme = f.read()
    except OSError as e:
        return [f"README not readable: {e}"]
    violations = []
    for name, where in sorted(collect_env_knobs(root).items()):
        if name not in readme:
            violations.append(
                f"{where}: env knob {name} is read but not documented "
                f"in README.md")
    return violations


# -- fault sites -------------------------------------------------------------

def _site_literals(root: Optional[str] = None
                   ) -> List[Tuple[str, str, bool]]:
    """(site-or-prefix, 'file:line', is_dynamic) for every fault-site
    declaration call: site("..."), _fault_site("..."), injector.site(f"..").
    """
    out = []
    for path, rel in _py_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        for n in ast.walk(tree):
            if not isinstance(n, ast.Call) or not n.args:
                continue
            fname = getattr(n.func, "id", getattr(n.func, "attr", ""))
            if fname not in ("site", "_fault_site", "_worker_fault_site"):
                continue
            arg = n.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((arg.value, f"{rel}:{n.lineno}", False))
            elif isinstance(arg, ast.JoinedStr) and arg.values and \
                    isinstance(arg.values[0], ast.Constant):
                out.append((str(arg.values[0].value),
                            f"{rel}:{n.lineno}", True))
    return out


def lint_fault_sites(root: Optional[str] = None,
                     readme_path: Optional[str] = None) -> List[str]:
    from ..fault.inject import DYNAMIC_SITES, KNOWN_SITES
    violations = []
    used_static: Set[str] = set()
    used_dynamic: Set[str] = set()
    for name, where, is_dynamic in _site_literals(root):
        if is_dynamic:
            prefix = next((p for p in DYNAMIC_SITES
                           if name.startswith(p) or p.startswith(name)),
                          None)
            if prefix is None:
                violations.append(
                    f"{where}: dynamic fault site f\"{name}...\" matches "
                    f"no registered DYNAMIC_SITES prefix")
            else:
                used_dynamic.add(prefix)
            continue
        if name in KNOWN_SITES:
            used_static.add(name)
            continue
        prefix = next((p for p in DYNAMIC_SITES if name.startswith(p)),
                      None)
        if prefix is not None:
            used_dynamic.add(prefix)
            continue
        violations.append(
            f"{where}: fault site {name!r} is not registered in "
            f"fault.inject.KNOWN_SITES (register it + document it in "
            f"the README fault-site table, or remove the site)")
    for name in sorted(set(KNOWN_SITES) - used_static):
        violations.append(
            f"fault.inject.KNOWN_SITES[{name!r}] has no call site left — "
            f"dead site: remove it from the registry and the README table")
    for prefix in sorted(set(DYNAMIC_SITES) - used_dynamic):
        violations.append(
            f"fault.inject.DYNAMIC_SITES[{prefix!r}] has no call site "
            f"left — dead site family")
    if readme_path is None:
        readme_path = os.path.join(os.path.dirname(package_root()),
                                   "README.md")
    try:
        with open(readme_path) as f:
            readme = f.read()
    except OSError as e:
        return violations + [f"README not readable: {e}"]
    for name in sorted(KNOWN_SITES):
        if f"`{name}`" not in readme:
            violations.append(
                f"registered fault site {name!r} is missing from the "
                f"README fault-site table")
    return violations


# -- threads -----------------------------------------------------------------

def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "Thread"
            and getattr(f.value, "id", None) == "threading") or \
        (isinstance(f, ast.Name) and f.id == "Thread")


def _target_key(target: ast.AST) -> Optional[str]:
    """A searchable suffix for the variable/attribute holding a Thread:
    'x' for `x = Thread(...)`, '_thread' for `self._thread = ...`."""
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute):
        return target.attr
    return None


def lint_threads(root: Optional[str] = None) -> List[str]:
    """Every threading.Thread must be daemon or provably joined.

    Accepted proofs, per module: `daemon=True` in the constructor call; a
    `<target>.daemon = True` assignment; or a `<target>.join(...)` call
    on the same name/attribute the Thread was assigned to."""
    violations = []
    for path, rel in _py_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        joined: Set[str] = set()
        daemoned: Set[str] = set()
        assigned: Dict[int, Optional[str]] = {}
        ctor_calls: List[ast.Call] = []
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) and _is_thread_ctor(n):
                ctor_calls.append(n)
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and _is_thread_ctor(n.value) and n.targets:
                assigned[id(n.value)] = _target_key(n.targets[0])
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "join":
                key = _target_key(n.func.value)
                if key:
                    joined.add(key)
            if isinstance(n, ast.Assign) and n.targets and \
                    isinstance(n.targets[0], ast.Attribute) and \
                    n.targets[0].attr == "daemon" and \
                    isinstance(n.value, ast.Constant) and \
                    n.value.value is True:
                key = _target_key(n.targets[0].value)
                if key:
                    daemoned.add(key)
        for call in ctor_calls:
            key = assigned.get(id(call))
            has_daemon_kw = any(
                kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True for kw in call.keywords)
            if has_daemon_kw:
                continue
            if key and (key in joined or key in daemoned):
                continue
            violations.append(
                f"{rel}:{call.lineno}: threading.Thread is neither "
                f"daemon=True nor provably joined"
                + (f" (target {key!r} has no .join()/.daemon=True in "
                   f"this module)" if key else " (not assigned — cannot "
                   "be joined)"))
    return violations


# -- event kinds -------------------------------------------------------------

def _imports_events_emit(tree: ast.AST) -> bool:
    """Does this module `from ...profiler.events import emit` (any
    relative depth)? Gates bare `emit("kind", ...)` calls so unrelated
    local emit() helpers (e.g. the ONNX node builder) don't lint."""
    for n in ast.walk(tree):
        if isinstance(n, ast.ImportFrom) and n.module and \
                n.module.endswith("events"):
            if any(a.name == "emit" for a in n.names):
                return True
    return False


def lint_event_kinds(root: Optional[str] = None) -> List[str]:
    """Every literal kind passed to an events-module `emit(...)` call
    (`events.emit`, `_events_mod.emit`, or an imported bare `emit`) must
    be declared (with a severity) in events.KIND_SEVERITY."""
    from ..profiler.events import KIND_SEVERITY
    violations = []
    for path, rel in _py_files(root):
        tree = _parse(path)
        if tree is None:
            continue
        bare_emit_is_events = _imports_events_emit(tree)
        for n in ast.walk(tree):
            if not isinstance(n, ast.Call) or not n.args:
                continue
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr == "emit":
                base = getattr(f.value, "id", "")
                if "event" not in base.lower():
                    continue  # some other object's .emit
            elif isinstance(f, ast.Name) and f.id == "emit":
                if not bare_emit_is_events:
                    continue
            else:
                continue
            arg = n.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            kind = arg.value
            if kind not in KIND_SEVERITY:
                violations.append(
                    f"{rel}:{n.lineno}: event kind {kind!r} is emitted "
                    f"but not declared in events.KIND_SEVERITY — declare "
                    f"its severity so obs_tail renders it")
    return violations


def run_all(root: Optional[str] = None,
            readme_path: Optional[str] = None) -> Dict[str, List[str]]:
    """All lints; {lint-name: violations}. Used by the CLI's --lint."""
    return {
        "env-knob-parses": lint_env_knob_parses(root),
        "env-knob-docs": lint_env_knob_docs(readme_path, root),
        "fault-sites": lint_fault_sites(root, readme_path),
        "threads": lint_threads(root),
        "event-kinds": lint_event_kinds(root),
    }
