"""Static analysis: program auditor + framework convention lints.

`auditor` vets compiled programs (jaxpr + lowered StableHLO) for perf
hazards at trace time — donation, dtype hygiene, sharding, executable
bloat — producing typed `findings` that land on the observability
plane. `conventions` is the AST-level lint pack over the package source
(env-knob parsing, fault-site registry, thread hygiene, event kinds).

Operator surfaces: `tools/program_audit.py` (offline CLI, CI gate via
--fail-on), the per-config `program_audit` block in bench.py, and the
`analysis_finding` event / `analysis_*` metric families.
"""
from .auditor import (AUDIT_ENV, audit_collectives_by_link, audit_program,
                      audit_sharding, enabled, maybe_audit, reset_seen)
from .findings import (CHECKS, SEVERITIES, AuditReport, Finding,
                       recent_reports)

__all__ = ["AUDIT_ENV", "audit_program", "audit_collectives_by_link",
           "audit_sharding", "enabled", "maybe_audit", "reset_seen",
           "AuditReport", "Finding", "CHECKS", "SEVERITIES",
           "recent_reports"]
