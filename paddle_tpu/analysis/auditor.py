"""Static program auditor: perf-hazard analysis over jaxpr + StableHLO.

The phi layer of the survey ships kernels behind a registry that can be
audited before anything runs; this is the JAX analogue. The auditor
traces (never executes) a program at its jit entry point and inspects
two artifacts:

* the **closed jaxpr** — op-level dtype flow, named-scope attribution
  (the PR-11 ``jax.named_scope`` metadata rides each equation's
  ``source_info.name_stack``), closure-captured constants, collective
  primitives;
* the **lowered StableHLO text** — the donation/aliasing table XLA
  actually accepted (``tf.aliasing_output`` / ``jax.buffer_donor`` arg
  attributes) vs what the caller requested (``Lowered.args_info``).

Checks (see findings.py for severity semantics):

1. **donation** — large (>= ``PADDLE_TPU_AUDIT_DONATE_MIN_BYTES``,
   default 1 MiB) input buffers that are dead after the step (an output
   of identical shape/dtype exists — the update pattern) but were not
   donated; and donations the caller requested that XLA rejected (no
   aliasing entry in the lowered text).
2. **dtype** — f64 anywhere (TPU-hostile); in a bf16-dominant region,
   f32 matmuls/convs and large silent float upcasts at op boundaries,
   attributed to the originating layer via named scopes.
3. **sharding** — collectives whose estimated per-step bytes exceed
   ``PADDLE_TPU_AUDIT_COLLECTIVE_BUDGET_MB``; and (via
   :func:`audit_sharding`) large params whose NamedSharding resolves to
   full replication while the mesh has a usable axis.
4. **bloat** — oversized constants baked into the program (host arrays
   captured by closure instead of passed as args,
   ``PADDLE_TPU_AUDIT_CONST_MIN_BYTES``) and retrace-risk static args.

Nothing here compiles or runs device code — it is trace-time analysis
that works on CPU CI, which is the point: every compiled TrainStep and
serving executable is vetted before a single device step. Runtime
integration is opt-in via ``PADDLE_TPU_AUDIT`` (``1``/``on`` audits the
compiled entry points — TrainStep, to_static, serving; ``all`` adds the
eager jit cache; each (entry, name) site is audited once per process).
"""
from __future__ import annotations

import re
import threading
import warnings
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.envparse import env_float, env_int, env_str
from .findings import AuditReport, Finding

__all__ = ["audit_program", "audit_collectives_by_link", "audit_sharding",
           "maybe_audit", "enabled", "AUDIT_ENV", "reset_seen"]

AUDIT_ENV = "PADDLE_TPU_AUDIT"

#: float widths for the upcast lattice (ml_dtypes bf16 has itemsize 2)
_FLOAT_ORDER = {"bfloat16": 2, "float16": 2, "float32": 4, "float64": 8}

#: primitives that move bytes across chips (the sharding-budget check)
_COLLECTIVE_PRIMS = ("psum", "psum2", "all_gather", "reduce_scatter",
                     "all_to_all", "ppermute", "psum_scatter", "pmax",
                     "pmin")

#: primitives whose compute dtype defines the "model region" and whose
#: f32 appearance inside a bf16 region is the classic AMP leak
_MATMUL_PRIMS = ("dot_general", "conv_general_dilated")


def _min_donate_bytes() -> int:
    return env_int("PADDLE_TPU_AUDIT_DONATE_MIN_BYTES", 1 << 20)


def _min_const_bytes() -> int:
    return env_int("PADDLE_TPU_AUDIT_CONST_MIN_BYTES", 1 << 20)


def _min_upcast_bytes() -> int:
    return env_int("PADDLE_TPU_AUDIT_UPCAST_MIN_BYTES", 1 << 20)


def _collective_budget_bytes() -> float:
    return env_float("PADDLE_TPU_AUDIT_COLLECTIVE_BUDGET_MB",
                     16 * 1024.0) * (1 << 20)


def _link_budget_bytes(link: str) -> float:
    """Per-link budgets: DCN is ~15x slower per chip than ICI, so the
    same byte count that is fine intra-slice is a hazard across slices."""
    if link == "dcn":
        return env_float("PADDLE_TPU_AUDIT_COLLECTIVE_BUDGET_DCN_MB",
                         1024.0) * (1 << 20)
    return env_float("PADDLE_TPU_AUDIT_COLLECTIVE_BUDGET_ICI_MB",
                     16 * 1024.0) * (1 << 20)


def enabled(entry: str) -> bool:
    """Is runtime auditing armed for this jit entry point?
    PADDLE_TPU_AUDIT: unset/0 = off; 1/on/trace = compiled entry points
    (train_step, to_static, serving_*); all = those plus the eager jit
    cache (every new eager op signature pays one extra trace)."""
    raw = (env_str(AUDIT_ENV, "") or "").strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return False
    if raw == "all":
        return True
    return entry != "eager"


# -- aval plumbing -----------------------------------------------------------

def _aval_nbytes(aval) -> int:
    try:
        size = int(np.prod(aval.shape)) if aval.shape else 1
        return size * aval.dtype.itemsize
    except Exception:
        return 0


def _dtype_name(dtype) -> str:
    try:
        return np.dtype(dtype).name
    except Exception:
        return str(dtype)


def _is_float(dtype) -> bool:
    return _dtype_name(dtype) in _FLOAT_ORDER


def _walk_eqns(jaxpr) -> Iterable[Tuple[Any, str]]:
    """Yield (eqn, scope) over `jaxpr` and every sub-jaxpr (pjit bodies,
    custom_vjp calls, scan/while/cond branches). `scope` is the
    named-scope path from the equation's source info — the PR-11
    attribution channel."""
    for eqn in jaxpr.eqns:
        try:
            scope = str(eqn.source_info.name_stack)
        except Exception:
            scope = ""
        yield eqn, scope
        for sub in _sub_jaxprs(eqn):
            for inner, inner_scope in _walk_eqns(sub):
                yield inner, (inner_scope or scope)


def _sub_jaxprs(eqn) -> List[Any]:
    out = []
    for v in eqn.params.values():
        core = getattr(v, "jaxpr", None)  # ClosedJaxpr
        if core is not None and hasattr(core, "eqns"):
            out.append(core)
        elif hasattr(v, "eqns"):          # bare Jaxpr
            out.append(v)
        elif isinstance(v, (tuple, list)):
            for x in v:
                core = getattr(x, "jaxpr", None)
                if core is not None and hasattr(core, "eqns"):
                    out.append(core)
                elif hasattr(x, "eqns"):
                    out.append(x)
    return out


def _flat_arg_labels(args_info) -> List[str]:
    """One human label per flattened argument, from tree paths."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(args_info)
    labels = []
    for path, _leaf in flat:
        labels.append(jax.tree_util.keystr(path) or "arg")
    return labels


# -- lowered-text parsing ----------------------------------------------------

# the attr dict may hold quoted values containing `}` (mhlo.sharding =
# "{devices=[2,1]<=[2]}" on sharded lowerings) — consume quoted strings
# atomically so the dict match doesn't truncate before the aliasing attr
_ARG_RE = re.compile(
    r"%arg(\d+):\s*tensor<[^>]*>\s*(\{(?:[^{}\"]|\"[^\"]*\")*\})?")


def _main_signature(text: str) -> str:
    """The argument list of the public @main func in StableHLO text
    (paren-balanced slice; `loc(...)` attributes nest parens)."""
    m = re.search(r"func\.func\s+(?:public\s+)?@main\s*\(", text)
    if not m:
        return ""
    i = m.end()
    depth = 1
    j = i
    while j < len(text) and depth:
        c = text[j]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        j += 1
    return text[i:j - 1]


def accepted_donations(lowered_text: str) -> set:
    """Flat arg indices whose lowering carries an aliasing/donation
    attribute — the donations XLA actually accepted."""
    sig = _main_signature(lowered_text)
    out = set()
    for m in _ARG_RE.finditer(sig):
        attrs = m.group(2) or ""
        if "tf.aliasing_output" in attrs or "jax.buffer_donor" in attrs:
            out.add(int(m.group(1)))
    return out


# -- the checks --------------------------------------------------------------

def _check_donation(report: AuditReport, flat_args, labels,
                    requested: set, accepted: set, out_avals):
    min_bytes = _min_donate_bytes()
    # outputs aliased by an ACCEPTED donation are consumed: they cannot
    # also justify flagging a second same-shaped input as dead
    out_pool: Dict[Tuple[tuple, str], int] = {}
    for aval in out_avals:
        key = (tuple(aval.shape), _dtype_name(aval.dtype))
        out_pool[key] = out_pool.get(key, 0) + 1
    for i in sorted(requested):
        if i >= len(flat_args):
            continue
        aval = flat_args[i]
        key = (tuple(aval.shape), _dtype_name(aval.dtype))
        if out_pool.get(key):
            out_pool[key] -= 1
    for i, aval in enumerate(flat_args):
        nbytes = _aval_nbytes(aval)
        key = (tuple(aval.shape), _dtype_name(aval.dtype))
        if i in requested:
            if i not in accepted:
                report.add(Finding(
                    check="donation", severity="high",
                    code="donation-rejected",
                    message=(f"donation of {key[1]}{list(aval.shape)} was "
                             f"requested but XLA's lowering carries no "
                             f"aliasing entry for it — the buffer is "
                             f"copied anyway"),
                    param=labels[i] if i < len(labels) else f"arg{i}",
                    nbytes=nbytes,
                    fix_hint=("make an output alias-compatible (same "
                              "shape/dtype) or drop the donation")))
            continue
        if nbytes < min_bytes:
            continue
        if out_pool.get(key):
            out_pool[key] -= 1
            report.add(Finding(
                check="donation", severity="high",
                code="undonated-large-input",
                message=(f"{key[1]}{list(aval.shape)} (~{nbytes >> 20} MiB) "
                         f"is replaced by a same-shaped output each step "
                         f"but is not donated — XLA must double-buffer "
                         f"it"),
                param=labels[i] if i < len(labels) else f"arg{i}",
                nbytes=nbytes,
                fix_hint="add this argument to donate_argnums"))


def _check_dtype(report: AuditReport, jaxpr):
    min_upcast = _min_upcast_bytes()
    # model-region dtype = the dominant float dtype by matmul/conv
    # OUTPUT bytes (elementwise ops follow whatever the matmuls feed)
    region_bytes: Dict[str, int] = {}
    f64_scopes: Dict[str, int] = {}
    upcasts: Dict[Tuple[str, str, str], Tuple[int, int]] = {}
    f32_matmuls: Dict[str, Tuple[int, int]] = {}
    for eqn, scope in _walk_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim in _MATMUL_PRIMS:
            # a matmul COMPUTES in its widest float operand dtype (XLA
            # upcasts mixed operands); outputs may legitimately be wider
            # (f32 accumulation), so the region is operand-defined
            in_fl = [v.aval for v in eqn.invars
                     if hasattr(v, "aval")
                     and _is_float(getattr(v.aval, "dtype", None))]
            if in_fl:
                dt = max((_dtype_name(a.dtype) for a in in_fl),
                         key=lambda d: _FLOAT_ORDER[d])
                region_bytes[dt] = region_bytes.get(dt, 0) + sum(
                    _aval_nbytes(a) for a in in_fl)
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if aval is None or not _is_float(getattr(aval, "dtype", None)):
                continue
            if _dtype_name(aval.dtype) == "float64":
                f64_scopes[scope] = f64_scopes.get(scope, 0) + 1
        if prim == "convert_element_type":
            try:
                src = eqn.invars[0].aval.dtype
                dst = eqn.params.get("new_dtype")
            except Exception:
                continue
            if not (_is_float(src) and _is_float(dst)):
                continue
            if _FLOAT_ORDER[_dtype_name(dst)] <= _FLOAT_ORDER[
                    _dtype_name(src)]:
                continue
            nbytes = _aval_nbytes(eqn.outvars[0].aval)
            if nbytes < min_upcast:
                continue
            key = (scope, _dtype_name(src), _dtype_name(dst))
            n, total = upcasts.get(key, (0, 0))
            upcasts[key] = (n + 1, total + nbytes)
    # region = bf16/f16 when narrow-float matmuls carry a meaningful
    # share of the compute (>= 20% of matmul bytes): the model INTENDS
    # mixed precision there, so wide matmuls are leaks. Judging by the
    # dominant dtype alone would let one big f32 leak redefine the
    # region and hide itself.
    total_mm = sum(region_bytes.values())
    narrow = sum(region_bytes.get(d, 0) for d in ("bfloat16", "float16"))
    if total_mm and narrow >= 0.2 * total_mm:
        region = "bfloat16" if region_bytes.get("bfloat16", 0) >= \
            region_bytes.get("float16", 0) else "float16"
    elif region_bytes:
        region = max(region_bytes, key=region_bytes.get)
    else:
        region = None
    if region in ("bfloat16", "float16"):
        # second pass: wide-OPERAND matmuls inside the narrow region.
        # Output dtype is deliberately ignored: f32 accumulation from
        # bf16 operands (preferred_element_type) is good practice, not a
        # leak — the MXU rate is set by what the operands are.
        for eqn, scope in _walk_eqns(jaxpr):
            if eqn.primitive.name not in _MATMUL_PRIMS:
                continue
            in_dts = [_dtype_name(v.aval.dtype) for v in eqn.invars
                      if hasattr(v, "aval")
                      and _is_float(getattr(v.aval, "dtype", None))]
            if in_dts and all(_FLOAT_ORDER[d] > _FLOAT_ORDER[region]
                              for d in in_dts):
                n, total = f32_matmuls.get(scope, (0, 0))
                f32_matmuls[scope] = (
                    n + 1, total + _aval_nbytes(eqn.outvars[0].aval))
    for scope, n in sorted(f64_scopes.items()):
        report.add(Finding(
            check="dtype", severity="high", code="f64-compute",
            message=(f"{n} op(s) compute in float64 — TPUs emulate f64 "
                     f"at a fraction of peak and double every buffer"),
            scope=scope,
            fix_hint="cast to float32/bfloat16 (or keep jax_enable_x64 "
                     "off)"))
    for (scope, src, dst), (n, total) in sorted(upcasts.items()):
        sev = "medium" if region in ("bfloat16", "float16") else "low"
        report.add(Finding(
            check="dtype", severity=sev, code="silent-upcast",
            message=(f"{n} convert(s) {src}->{dst} totalling "
                     f"~{total >> 20} MiB at op boundaries"),
            scope=scope, nbytes=total,
            fix_hint=(f"keep the region in {region or src}: check the "
                      f"layer's param/activation dtypes at this scope")))
    for scope, (n, total) in sorted(f32_matmuls.items()):
        report.add(Finding(
            check="dtype", severity="medium", code="f32-matmul-in-bf16",
            message=(f"{n} float32 matmul/conv op(s) (~{total >> 20} MiB "
                     f"out) inside a {region} model region — the MXU "
                     f"runs these at half rate"),
            scope=scope, nbytes=total,
            fix_hint="cast the operands (amp_dtype / maybe_cast) at this "
                     "scope"))


def _check_collectives(report: AuditReport, jaxpr):
    budget = _collective_budget_bytes()
    if budget <= 0:
        return
    per_scope: Dict[Tuple[str, str], int] = {}
    total = 0
    for eqn, scope in _walk_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim not in _COLLECTIVE_PRIMS:
            continue
        nbytes = max(
            sum(_aval_nbytes(v.aval) for v in eqn.invars
                if hasattr(v, "aval")),
            sum(_aval_nbytes(v.aval) for v in eqn.outvars))
        total += nbytes
        key = (scope, prim)
        per_scope[key] = per_scope.get(key, 0) + nbytes
    if total > budget:
        top = sorted(per_scope.items(), key=lambda kv: -kv[1])[:3]
        detail = ", ".join(f"{prim}@{scope or '<root>'}"
                           f"~{b >> 20}MiB" for (scope, prim), b in top)
        report.add(Finding(
            check="sharding", severity="high",
            code="collective-budget-exceeded",
            message=(f"collectives move ~{total >> 20} MiB per step, over "
                     f"the {int(budget) >> 20} MiB budget "
                     f"(top: {detail})"),
            nbytes=total,
            fix_hint=("shard the offending tensors further, fuse "
                      "collectives, or raise "
                      "PADDLE_TPU_AUDIT_COLLECTIVE_BUDGET_MB")))


def _check_bloat(report: AuditReport, consts, static_args=None):
    min_bytes = _min_const_bytes()
    small_total = 0
    for i, c in enumerate(consts):
        nbytes = int(getattr(c, "nbytes", 0) or 0)
        shape = tuple(getattr(c, "shape", ()) or ())
        dtype = _dtype_name(getattr(c, "dtype", "?"))
        if nbytes >= min_bytes:
            report.add(Finding(
                check="bloat", severity="high", code="baked-constant",
                message=(f"{dtype}{list(shape)} (~{nbytes >> 20} MiB) is "
                         f"baked into the executable as a constant — a "
                         f"host array captured by closure is re-uploaded "
                         f"with every executable that embeds it"),
                param=f"const{i}", nbytes=nbytes,
                fix_hint="pass the array as an argument (or a donated "
                         "buffer) instead of capturing it"))
        else:
            small_total += nbytes
    if small_total >= 4 * min_bytes:
        report.add(Finding(
            check="bloat", severity="medium", code="constant-accretion",
            message=(f"{len(consts)} captured constants total "
                     f"~{small_total >> 20} MiB (each under the "
                     f"baked-constant threshold)"),
            nbytes=small_total,
            fix_hint="thread recurring host state as arguments"))
    for name, val in (static_args or {}).items():
        risky = isinstance(val, float) or (
            isinstance(val, (tuple, list)) and len(val) > 16)
        if risky:
            report.add(Finding(
                check="bloat", severity="low", code="retrace-risk-static",
                message=(f"static arg {name!r} = {type(val).__name__} — "
                         f"every distinct value recompiles the program "
                         f"(floats/high-cardinality values churn)"),
                param=str(name),
                fix_hint="make it a traced argument or quantize its "
                         "value space"))


# -- entry points ------------------------------------------------------------

def audit_program(fn, args: Sequence, kwargs: Optional[dict] = None, *,
                  donate_argnums: Sequence[int] = (),
                  static_args: Optional[dict] = None,
                  name: str = "program", entry: str = "offline",
                  emit: bool = True) -> AuditReport:
    """Trace `fn(*args, **kwargs)` and audit the program statically.

    `donate_argnums` are the TOP-LEVEL argument positions the caller
    donates (exactly what it passes to jax.jit) — the auditor compares
    them against the aliasing table XLA accepted. Findings are emitted
    to events/metrics unless `emit=False`. Never executes the program.
    """
    import jax

    kwargs = kwargs or {}
    report = AuditReport(name=name, entry=entry)

    with warnings.catch_warnings():
        # the rejected-donation warning is re-raised as a typed finding
        warnings.simplefilter("ignore")
        # ONE trace serves both artifacts: Traced.jaxpr carries the
        # closed jaxpr (with captured consts) and .lower() reuses the
        # trace — tracing twice doubled audit cost at every entry point
        traced = jax.jit(
            fn, donate_argnums=tuple(donate_argnums)).trace(*args, **kwargs)
        closed = traced.jaxpr
        lowered = traced.lower()
    text = lowered.as_text()

    flat_info, _ = jax.tree_util.tree_flatten(lowered.args_info)
    flat_avals = [getattr(i, "aval", i) for i in flat_info]
    requested = {i for i, info in enumerate(flat_info)
                 if bool(getattr(info, "donated", False))}
    labels = _flat_arg_labels(lowered.args_info)
    out_avals = [v.aval for v in closed.jaxpr.outvars]

    _check_donation(report, flat_avals, labels, requested,
                    accepted_donations(text), out_avals)
    _check_dtype(report, closed.jaxpr)
    _check_collectives(report, closed.jaxpr)
    _check_bloat(report, closed.consts, static_args)

    if emit:
        report.emit()
    return report


def audit_collectives_by_link(fn, args: Sequence,
                              kwargs: Optional[dict] = None, *,
                              donate_argnums: Sequence[int] = (),
                              cluster=None, name: str = "program",
                              entry: str = "collectives",
                              emit: bool = True) -> AuditReport:
    """Per-link (ici/dcn) collective-bytes budget over the COMPILED
    program. `audit_program`'s jaxpr check only sees explicit collective
    primitives; the collectives of a GSPMD/shard_map-partitioned program
    (the TP decode path) are inserted by the partitioner, so this check
    compiles (nothing executes — XLA donation is a compile-time aliasing
    hint) and prices the optimized HLO's collectives by the link class
    their replica groups actually cross, via the cluster mapper's
    slice-major topology. Budgets:
    ``PADDLE_TPU_AUDIT_COLLECTIVE_BUDGET_ICI_MB`` (default 16 GiB) and
    ``_DCN_MB`` (default 1 GiB); the cluster shape comes from
    ``PADDLE_TPU_NUM_SLICES`` (single-slice clusters bill everything to
    ici) unless an explicit `cluster` is passed. The report carries the
    measured totals on ``report.link_bytes``."""
    import jax

    from ..distributed.auto_parallel.cluster import Cluster, Mapper

    kwargs = kwargs or {}
    if cluster is None:
        ndev = jax.device_count()
        n_slices = max(1, env_int("PADDLE_TPU_NUM_SLICES", 1))
        cluster = Cluster(n_slices=n_slices,
                          chips_per_slice=max(1, ndev // n_slices))
    report = AuditReport(name=name, entry=entry)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        compiled = jax.jit(
            fn, donate_argnums=tuple(donate_argnums)).lower(
                *args, **kwargs).compile()
    ici, dcn = Mapper(cluster).collective_bytes_by_link(compiled)
    for link, nbytes, bw in (("ici", ici, cluster.ici_bw),
                             ("dcn", dcn, cluster.dcn_bw)):
        budget = _link_budget_bytes(link)
        if budget <= 0 or nbytes <= budget:
            continue
        report.add(Finding(
            check="sharding", severity="high",
            code=f"collective-budget-exceeded-{link}",
            message=(f"compiled collectives move ~{int(nbytes) >> 20} MiB "
                     f"per step over {link} "
                     f"(~{nbytes / bw * 1e3:.2f} ms at "
                     f"{bw / 1e9:.0f} GB/s), over the "
                     f"{int(budget) >> 20} MiB {link} budget"),
            nbytes=int(nbytes),
            fix_hint=(f"reshard so the traffic rides a faster link, fuse "
                      f"collectives, or raise "
                      f"PADDLE_TPU_AUDIT_COLLECTIVE_BUDGET_"
                      f"{link.upper()}_MB")))
    report.link_bytes = {"ici": float(ici), "dcn": float(dcn)}
    if emit:
        report.emit()
    return report


def audit_sharding(params: Dict[str, Any],
                   mesh_axes: Optional[Dict[str, int]] = None, *,
                   name: str = "params", entry: str = "offline",
                   min_bytes: Optional[int] = None,
                   emit: bool = True) -> AuditReport:
    """Audit a param tree's shardings: a large param whose NamedSharding
    resolves to full replication while the mesh has a usable (>1) axis
    that divides one of its dims is memory the fleet pays `world` times.

    `params` leaves may be jax.Arrays (sharding read off the array) or
    (shape, dtype, partition-spec) triples for metadata-level audits —
    which is what CPU CI uses, since a single-device process cannot
    build a >1 mesh. `mesh_axes` maps axis name -> size; when None it is
    read from the first NamedSharding leaf's mesh."""
    import jax

    report = AuditReport(name=name, entry=entry)
    if min_bytes is None:
        min_bytes = env_int("PADDLE_TPU_AUDIT_REPLICATED_MIN_BYTES",
                            1 << 20)

    flat, _ = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda x: isinstance(x, tuple) and len(x) == 3)
    leaves = []
    axes = dict(mesh_axes or {})
    for path, leaf in flat:
        label = jax.tree_util.keystr(path) or "param"
        if isinstance(leaf, tuple) and len(leaf) == 3:
            shape, dtype, spec = leaf
            leaves.append((label, tuple(shape), np.dtype(dtype), spec))
            continue
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        mesh = getattr(sharding, "mesh", None)
        if mesh is not None and not axes:
            axes = dict(mesh.shape)
        leaves.append((label, tuple(leaf.shape), np.dtype(leaf.dtype),
                       spec))
    usable = {ax: n for ax, n in axes.items() if int(n) > 1}
    if usable:
        for label, shape, dtype, spec in leaves:
            nbytes = int(np.prod(shape)) * dtype.itemsize if shape else \
                dtype.itemsize
            if nbytes < min_bytes:
                continue
            spec_parts = tuple(spec) if spec is not None else ()
            if any(p is not None for p in spec_parts):
                continue  # sharded on at least one dim
            fitting = [ax for ax, n in usable.items()
                       if any(d % int(n) == 0 and d >= int(n)
                              for d in shape)]
            if not fitting:
                continue
            report.add(Finding(
                check="sharding", severity="high",
                code="replicated-param",
                message=(f"{dtype.name}{list(shape)} (~{nbytes >> 20} "
                         f"MiB) is fully replicated though mesh "
                         f"axis(es) {fitting} could shard it — every "
                         f"chip holds a full copy"),
                param=label, nbytes=nbytes,
                fix_hint=(f"give it a PartitionSpec over "
                          f"{fitting[0]!r}")))
    if emit:
        report.emit()
    return report


# -- runtime hook ------------------------------------------------------------

_seen_lock = threading.Lock()
_seen: set = set()


def reset_seen():
    """Test hook: allow a site to be re-audited in this process."""
    with _seen_lock:
        _seen.clear()


def maybe_audit(entry: str, name: str, fn, args: Sequence,
                kwargs: Optional[dict] = None, *,
                donate_argnums: Sequence[int] = ()) -> Optional[AuditReport]:
    """Audit a jit entry point once per (entry, name) when
    PADDLE_TPU_AUDIT arms it. Swallows every failure — an auditor bug
    must never take down the training step it vets."""
    if not enabled(entry):
        return None
    key = (entry, name)
    with _seen_lock:
        if key in _seen:
            return None
        _seen.add(key)
    try:
        return audit_program(fn, args, kwargs, donate_argnums=donate_argnums,
                             name=name, entry=entry)
    except Exception as e:  # noqa: BLE001 — by contract
        warnings.warn(f"program audit of {entry}:{name} failed "
                      f"({type(e).__name__}: {e}); skipping")
        return None
