"""Typed findings for the static program auditor.

A :class:`Finding` is one perf hazard (or convention violation) the
auditor proved from the jaxpr / lowered StableHLO text of a compiled
program — without executing it. Severity semantics (the CLI's
``--fail-on`` and the tier-1 gate key off these):

* ``high``   — a real, avoidable perf/memory hazard on the audited path
  (undonated large dead buffer, rejected donation, f64 compute, a
  replicated param with a usable mesh axis, a host array baked into the
  executable). The shipped models must audit high-clean.
* ``medium`` — likely waste that needs a human look (large silent float
  upcast, f32 matmul inside a bf16 region, collective-bytes budget
  exceeded).
* ``low``    — style/risk notes (retrace-prone static args).
* ``info``   — context the auditor wants on the record.

Every finding lands on the PR-6 observability plane:
``analysis_finding`` events (severity mapped high->error, medium->warn,
low->info, info->debug) and the
``analysis_findings_total{check=,severity=}`` metric family; audits
themselves count in ``analysis_audits_total{entry=}``.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..profiler import events as _events_mod
from ..profiler import metrics as _metrics_mod

__all__ = ["Finding", "AuditReport", "SEVERITIES", "CHECKS",
           "recent_reports"]

#: ascending order (the CLI's --fail-on threshold indexes into this)
SEVERITIES = ("info", "low", "medium", "high")

#: the check families the auditor implements
CHECKS = ("donation", "dtype", "sharding", "bloat")

_EVENT_SEVERITY = {"high": "error", "medium": "warn", "low": "info",
                   "info": "debug"}

_REG = _metrics_mod.default_registry()
_M_FINDINGS = _REG.counter(
    "analysis_findings_total",
    "static program-auditor findings by check and severity")
_M_AUDITS = _REG.counter(
    "analysis_audits_total",
    "program audits run, by jit entry point")

#: newest emitted audit reports, for the ObservabilityServer /snapshot
#: endpoint (bounded; a long-lived daemon auditing every engine it
#: builds must not grow this without limit)
_RECENT_REPORTS: "deque[dict]" = deque(maxlen=16)


def recent_reports() -> List[dict]:
    """The newest emitted audit reports (dict form, oldest first) —
    what the ObservabilityServer surfaces under `program_audit`. Each
    entry is `AuditReport.to_dict(max_findings=8)` plus an `emitted_ts`
    wall-clock stamp."""
    return list(_RECENT_REPORTS)


@dataclass
class Finding:
    """One auditor finding: what, how bad, where, and how to fix it."""

    check: str            # one of CHECKS
    severity: str         # one of SEVERITIES
    code: str             # stable slug, e.g. "undonated-large-input"
    message: str          # human sentence stating the hazard
    param: str = ""       # offending arg/param/const path or op name
    scope: str = ""       # named-scope attribution (PR-11 metadata)
    nbytes: int = 0       # size of the offending buffer (0 = n/a)
    fix_hint: str = ""    # what to change

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {self.severity!r}")
        if self.check not in CHECKS:
            raise ValueError(f"check must be one of {CHECKS}, "
                             f"got {self.check!r}")

    def to_dict(self) -> dict:
        d = {"check": self.check, "severity": self.severity,
             "code": self.code, "message": self.message}
        for k in ("param", "scope", "fix_hint"):
            v = getattr(self, k)
            if v:
                d[k] = v
        if self.nbytes:
            d["nbytes"] = int(self.nbytes)
        return d

    def __str__(self):
        where = f" [{self.param}]" if self.param else ""
        scope = f" (scope: {self.scope})" if self.scope else ""
        hint = f" — fix: {self.fix_hint}" if self.fix_hint else ""
        return (f"{self.severity.upper():<6} {self.check}/{self.code}"
                f"{where}{scope}: {self.message}{hint}")


@dataclass
class AuditReport:
    """All findings of one program audit, plus identity of the program."""

    name: str                      # program label (e.g. "GPT#1")
    entry: str                     # jit entry audited (train_step, ...)
    findings: List[Finding] = field(default_factory=list)

    def add(self, finding: Finding):
        self.findings.append(finding)

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    def by_severity(self, floor: str) -> List[Finding]:
        """Findings at or above `floor` severity."""
        lo = SEVERITIES.index(floor)
        return [f for f in self.findings
                if SEVERITIES.index(f.severity) >= lo]

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_dict(self, max_findings: Optional[int] = None) -> dict:
        ranked = sorted(
            self.findings,
            key=lambda f: -SEVERITIES.index(f.severity))
        if max_findings is not None:
            ranked = ranked[:max_findings]
        return {"name": self.name, "entry": self.entry,
                "counts": self.counts(),
                "findings": [f.to_dict() for f in ranked]}

    def emit(self):
        """Land this report on the observability plane: one
        `analysis_finding` event per finding + the metric families.
        Never raises (audits run inside training entry points)."""
        try:
            rec = self.to_dict(max_findings=8)
            rec["emitted_ts"] = time.time()
            _RECENT_REPORTS.append(rec)
        except Exception:
            pass
        try:
            if _metrics_mod.enabled():
                _M_AUDITS.inc(entry=self.entry)
                for f in self.findings:
                    _M_FINDINGS.inc(check=f.check, severity=f.severity)
            for f in self.findings:
                _events_mod.emit(
                    "analysis_finding",
                    severity=_EVENT_SEVERITY[f.severity],
                    program=self.name, entry=self.entry,
                    check=f.check, code=f.code, finding_severity=f.severity,
                    param=f.param, scope=f.scope, nbytes=int(f.nbytes),
                    message=f.message, fix_hint=f.fix_hint)
        except Exception:
            pass

    def render(self) -> str:
        """Human table for the CLI."""
        if not self.findings:
            return f"{self.name} [{self.entry}]: clean (0 findings)"
        lines = [f"{self.name} [{self.entry}]: "
                 f"{len(self.findings)} finding(s)"]
        for f in sorted(self.findings,
                        key=lambda f: -SEVERITIES.index(f.severity)):
            lines.append("  " + str(f))
        return "\n".join(lines)
