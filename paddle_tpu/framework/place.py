"""Device placement.

TPU-native equivalent of the reference Place taxonomy
(`/root/reference/paddle/fluid/platform/place.h`) and
`paddle.set_device` (`python/paddle/device/__init__.py`). Places map onto
`jax.Device` objects; the default device is process-global, mirroring the
reference's `DeviceContextPool` current-device semantics.
"""
from __future__ import annotations

import jax


class Place:
    """Base place. Wraps a jax.Device."""

    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    @property
    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _platform_of(d) == self.device_type]
        if not devs:
            # Graceful fallback (e.g. TPUPlace requested on a CPU-only host).
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


def _platform_of(dev: jax.Device) -> str:
    p = dev.platform
    # the axon/libtpu plugin reports 'tpu' (sometimes 'axon'); normalize
    if p in ("tpu", "axon"):
        return "tpu"
    return p


class CPUPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    device_type = "tpu"


# Aliases kept for API familiarity with the reference's device taxonomy
# (`platform/place.h`): on this framework the accelerator is a TPU, and
# "pinned" host memory is ordinary host memory (XLA stages its own
# transfers).
CUDAPlace = TPUPlace
NPUPlace = TPUPlace


class CUDAPinnedPlace(Place):
    device_type = "cpu"

    def __init__(self):
        super().__init__(0)


class CustomPlace(Place):
    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


_expected_place: Place | None = None


def set_device(device) -> Place:
    """paddle.set_device('tpu:0' | 'cpu' | 'tpu')."""
    global _expected_place
    if isinstance(device, Place):
        _expected_place = device
        return device
    if not isinstance(device, str):
        raise TypeError(f"device must be str or Place, got {type(device)}")
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = name.lower()
    if name == "cpu":
        _expected_place = CPUPlace()
    elif name in ("tpu", "gpu", "cuda", "xpu", "npu"):
        # accelerator names all route to the TPU on this framework
        _expected_place = TPUPlace(idx)
    else:
        _expected_place = CustomPlace(name, idx)
    return _expected_place


def get_device() -> str:
    p = get_expected_place()
    return f"{p.device_type}:{p.device_id}" if p.device_type != "cpu" else "cpu"


def get_expected_place() -> Place:
    global _expected_place
    if _expected_place is None:
        d = jax.devices()[0]
        _expected_place = CPUPlace() if _platform_of(d) == "cpu" else TPUPlace(0)
    return _expected_place


def is_compiled_with_tpu() -> bool:
    return any(_platform_of(d) == "tpu" for d in jax.devices())


def device_count() -> int:
    return len(jax.devices())
