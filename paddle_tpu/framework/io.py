"""Checkpoint save/load.

Reference: `paddle.save/load` (`/root/reference/python/paddle/framework/io.py:568,784`)
— pickled nested state_dicts of numpy arrays. Distributed/sharded arrays are
gathered to host numpy at save time; `paddle_tpu.distributed.checkpoint`
layers orbax-style sharded checkpoints on top for multi-host.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor
from .param import Parameter

# process umask, captured once while single-threaded: mkstemp creates 0600
# files, but a published checkpoint must keep the umask-default mode the
# plain open() used to give (group-readable checkpoints feed eval jobs)
_UMASK = os.umask(0)
os.umask(_UMASK)


def _atomic_write(path: str, payload: bytes):
    """The one atomic-publish protocol for checkpoint-like files (also used
    by distributed/checkpoint.py): unique tmp in the target dir, umask-
    default mode, `os.replace` — a crash mid-write never leaves a torn
    file at the published path, concurrent writers never share a tmp."""
    import tempfile
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".tmp.")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.chmod(tmp, 0o666 & ~_UMASK)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj.data),
                "stop_gradient": obj.stop_gradient,
                "is_param": isinstance(obj, Parameter), "name": obj.name}
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("is_param") else Tensor
            t = cls(jnp.asarray(obj["data"]))
            if not obj.get("is_param"):
                t.stop_gradient = obj.get("stop_gradient", True)
            t.name = obj.get("name")
            return t
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


_ENC_MAGIC = b"PDTPUAES1\x00"


def _aes_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    """AES-CTR via the native lib (csrc/crypto.cc — reference
    `framework/io/crypto/cipher.cc` AES model-file cipher). Symmetric:
    one call both encrypts and decrypts."""
    import ctypes

    from .. import _native
    lib = _native.load()
    if len(key) not in (16, 24, 32):
        raise ValueError("cipher key must be 16/24/32 bytes (AES-128/192/256)")
    out = ctypes.create_string_buffer(len(data))
    u8 = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.pd_aes_ctr_crypt(
        ctypes.cast(ctypes.c_char_p(key), u8), len(key),
        ctypes.cast(ctypes.c_char_p(iv), u8),
        ctypes.cast(ctypes.c_char_p(data), u8),
        ctypes.cast(out, u8), len(data))
    if rc != 0:
        raise RuntimeError("aes_ctr_crypt failed")
    return out.raw


def save(obj, path, protocol=4, cipher_key: bytes = None, **configs):
    """`cipher_key` (16/24/32 bytes) encrypts the checkpoint with AES-CTR
    (reference `framework/io/crypto/` model encryption for industrial PS
    deployments); a random IV is stored in the header."""
    payload = pickle.dumps(_to_saveable(obj), protocol=protocol)
    if cipher_key is not None:
        iv = os.urandom(16)
        payload = _ENC_MAGIC + iv + _aes_ctr(cipher_key, iv, payload)
    _atomic_write(path, payload)


def _is_reference_format(raw) -> bool:
    return isinstance(raw, dict) and (
        "StructuredToParameterName@@" in raw
        or "UnpackBigParamInfor@@" in raw)


def _decode_reference(obj, return_numpy):
    """Decode a checkpoint written by the reference's `paddle.save`
    (`/root/reference/python/paddle/framework/io.py:568`): state_dict
    values are plain ndarrays (`_build_saved_state_dict`, io.py:41), big
    params are split into `key@@.N` slices with an `UnpackBigParamInfor@@`
    manifest (`fluid/io.py:1768`), and Tensors nested in other containers
    pickle via `reduce_varbase` to a `((name, ndarray),)` tuple
    (io.py:240). The pickles contain only numpy + builtins, so they load
    without the reference installed."""
    if isinstance(obj, dict):
        obj = dict(obj)
        info = obj.pop("UnpackBigParamInfor@@", None)
        if info:
            for key, val in info.items():  # re-pack (fluid/io.py:1804)
                slices = [obj.pop(p) for p in val["slices"]]
                obj[key] = np.concatenate(
                    [np.asarray(s) for s in slices]).reshape(
                        val["OriginShape"])
        obj.pop("StructuredToParameterName@@", None)
        return {k: _decode_reference(v, return_numpy) for k, v in obj.items()}
    if (isinstance(obj, tuple) and len(obj) == 1
            and isinstance(obj[0], tuple) and len(obj[0]) == 2
            and isinstance(obj[0][0], str)
            and isinstance(obj[0][1], np.ndarray)):
        arr = obj[0][1]  # reduce_varbase encoding: ((name, data),)
        if return_numpy:
            return arr
        t = Tensor(jnp.asarray(arr))
        t.name = obj[0][0]
        return t
    if isinstance(obj, (list, tuple)):
        return type(obj)(_decode_reference(v, return_numpy) for v in obj)
    if isinstance(obj, np.ndarray):
        return obj if return_numpy else Tensor(jnp.asarray(obj))
    return obj


def match_state_dict(layer, state_dict):
    """Name-map a (possibly prefixed) reference state_dict onto `layer`.

    Zoo structured names already line up with the reference models'
    (resnet `conv1/bn1/layerN.M/fc`, BertModel
    `embeddings.*/encoder.layers.N.*/pooler.dense`); ecosystem checkpoints
    often carry a wrapping prefix (`bert.`) or head keys (`cls.*`). This
    finds the prefix with the best key overlap, strips it, and returns
    (matched, missing, unexpected) — apply with `layer.set_state_dict`.
    """
    want = set(dict(layer.state_dict()).keys())
    keys = list(state_dict.keys())
    prefixes = {""}
    for k in keys:
        parts = k.split(".")
        for i in (1, 2):
            if len(parts) > i:
                prefixes.add(".".join(parts[:i]) + ".")
    def overlap(pref):
        return sum(1 for k in keys
                   if k.startswith(pref) and k[len(pref):] in want)
    best = max(prefixes, key=overlap)
    matched = {k[len(best):]: v for k, v in state_dict.items()
               if k.startswith(best) and k[len(best):] in want}
    missing = sorted(want - set(matched))
    unexpected = sorted(k for k in keys
                        if not (k.startswith(best)
                                and k[len(best):] in want))
    return matched, missing, unexpected


def load(path, return_numpy=False, cipher_key: bytes = None, **configs):
    with open(path, "rb") as f:
        data = f.read()
    if data.startswith(_ENC_MAGIC):
        if cipher_key is None:
            raise ValueError(
                f"{path} is AES-encrypted: pass cipher_key=... to load")
        iv = data[len(_ENC_MAGIC):len(_ENC_MAGIC) + 16]
        data = _aes_ctr(cipher_key, iv, data[len(_ENC_MAGIC) + 16:])
    raw = pickle.loads(data)
    if _is_reference_format(raw):
        return _decode_reference(raw, return_numpy)
    return _from_saveable(raw, return_numpy=return_numpy)
