"""Checkpoint save/load.

Reference: `paddle.save/load` (`/root/reference/python/paddle/framework/io.py:568,784`)
— pickled nested state_dicts of numpy arrays. Distributed/sharded arrays are
gathered to host numpy at save time; `paddle_tpu.distributed.checkpoint`
layers orbax-style sharded checkpoints on top for multi-host.
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor
from .param import Parameter


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj.data),
                "stop_gradient": obj.stop_gradient,
                "is_param": isinstance(obj, Parameter), "name": obj.name}
    if isinstance(obj, jax.Array):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            cls = Parameter if obj.get("is_param") else Tensor
            t = cls(jnp.asarray(obj["data"]))
            if not obj.get("is_param"):
                t.stop_gradient = obj.get("stop_gradient", True)
            t.name = obj.get("name")
            return t
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        raw = pickle.load(f)
    return _from_saveable(raw, return_numpy=return_numpy)
