"""Global flag registry.

Reference parity: the reference's exported gflags
(`platform/flags.cc:35` `PADDLE_DEFINE_EXPORTED_*`, read/written from Python
via `core.globals()` / `pybind/global_value_getter_setter.cc`, env `FLAGS_*`
parsed at import in `fluid/__init__.py`). Here: a typed in-process registry;
`FLAGS_*` environment variables override defaults at import; behavioral flags
are consulted by the runtime (e.g. `FLAGS_check_nan_inf` hooks every op
dispatch, like the reference's `CheckOpHasNanOrInf`
`framework/details/nan_inf_utils.h:29`).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Union


class _Flag:
    __slots__ = ("name", "value", "default", "type", "help")

    def __init__(self, name, default, help=""):
        self.name = name
        self.default = default
        self.value = default
        self.type = type(default)
        self.help = help


_REGISTRY: Dict[str, _Flag] = {}


def define_flag(name: str, default, help: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    flag = _Flag(name, default, help)
    env = os.environ.get(name)
    if env is not None:
        flag.value = _parse(env, flag.type)
    _REGISTRY[name] = flag
    return flag


def _parse(s: str, ty):
    if ty is bool:
        return s.lower() in ("1", "true", "yes", "on")
    return ty(s)


def get_flags(flags: Union[str, List[str]]) -> Dict[str, Any]:
    """paddle.get_flags parity."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        if not name.startswith("FLAGS_"):
            name = "FLAGS_" + name
        if name not in _REGISTRY:
            raise ValueError(f"unknown flag {name}")
        out[name] = _REGISTRY[name].value
    return out


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags parity."""
    for name, value in flags.items():
        if not name.startswith("FLAGS_"):
            name = "FLAGS_" + name
        if name not in _REGISTRY:
            raise ValueError(f"unknown flag {name}")
        flag = _REGISTRY[name]
        flag.value = _parse(value, flag.type) if isinstance(value, str) else \
            flag.type(value)
        _on_flag_set(name, flag.value)


def flag(name: str):
    """Fast internal read."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    return _REGISTRY[name].value


def all_flags() -> Dict[str, Any]:
    return {n: f.value for n, f in _REGISTRY.items()}


def _on_flag_set(name: str, value):
    # behavioral side effects
    if name == "FLAGS_check_nan_inf":
        # Routes to the training-health plane (profiler/health.py), NOT to
        # jax_debug_nans: the eager dispatch post-check reads this flag per
        # call (so a runtime set_flags arms it immediately), compiled
        # TrainSteps fold the in-graph sentinel on next construction, and
        # here we arm the layer-path attribution stack. jax_debug_nans —
        # crash-only, no attribution, largely inert inside compiled
        # steps — is the explicit FLAGS_debug_nans escape hatch below.
        try:
            import sys
            h = sys.modules.get("paddle_tpu.profiler.health")
            if h is not None:
                h.set_eager_check(bool(value))
        except Exception:
            pass
    elif name == "FLAGS_debug_nans":
        try:
            import jax
            jax.config.update("jax_debug_nans", bool(value))
        except Exception:
            pass
    elif name == "FLAGS_compile_cache_dir":
        _apply_compile_cache_dir(value)


def _apply_compile_cache_dir(path):
    """Point jax's persistent compilation cache at `path` (empty = off).

    Makes elastic relaunches / serving cold-starts compile once per
    program instead of once per process (ROADMAP item 5), and turns the
    already-exported `xla_compile_cache_events_total{event=}` counters
    into real hit/miss numbers (profiler/compile_watch.py listens on the
    jax.monitoring channel the cache feeds). The size/time floors are
    dropped so every executable is cached — the cache exists for
    multi-minute pod-scale compiles, but CI exercises the same path with
    tiny ones."""
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", path or None)
        if path:
            # each floor knob guarded on its own: a jax version missing one
            # must not skip the reset_cache() below (without which a
            # runtime enable is silently ignored — see comment there)
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0)
            except Exception:
                pass
            try:
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1)
            except Exception:
                pass  # knob not present on older jax
        try:
            # jax latches its cache handle on the FIRST compile of the
            # process; without a reset, enabling the dir after any compile
            # (set_flags at runtime, not env) is silently ignored
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass
    except Exception:
        pass  # jax absent / too old: the flag stays readable, inert


# ---------------------------------------------------------------------------
# Flag definitions (subset of platform/flags.cc with TPU-meaningful semantics)
# ---------------------------------------------------------------------------
define_flag("FLAGS_check_nan_inf", False,
            "training-health numerics plane (reference nan_inf_utils): "
            "eager dispatch post-checks every op output and attributes the "
            "first NaN/Inf to op + layer path (tensor_health event); "
            "compiled TrainSteps fold the in-graph health sentinel "
            "(profiler/health.py). See also PADDLE_TPU_HEALTH=1 "
            "(sentinel-only) and FLAGS_debug_nans (raw jax_debug_nans)")
define_flag("FLAGS_debug_nans",
            os.environ.get("PADDLE_TPU_DEBUG_NANS", "").lower() in
            ("1", "true", "yes", "on"),
            "escape hatch: jax's own jax_debug_nans (crash-only, no "
            "attribution, mostly inert inside compiled steps — prefer "
            "FLAGS_check_nan_inf / PADDLE_TPU_HEALTH). Set via "
            "PADDLE_TPU_DEBUG_NANS=1 or set_flags")
define_flag("FLAGS_benchmark", False, "synchronize after each op for timing")
define_flag("FLAGS_use_pallas_kernels", True,
            "use Pallas TPU kernels (flash attention, fused ops) when shapes "
            "allow; pure-XLA fallback otherwise")
define_flag("FLAGS_allocator_strategy", "auto_growth",
            "parity flag (XLA owns TPU HBM allocation; informational)")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92,
            "parity flag; maps to XLA_PYTHON_CLIENT_MEM_FRACTION if set "
            "before first device use")
define_flag("FLAGS_use_standalone_executor", True,
            "static.Executor compiles whole programs as one XLA executable")
define_flag("FLAGS_max_inmemory_prefetch", 2,
            "DataLoader device prefetch depth (BufferedReader equivalent)")
define_flag("FLAGS_sync_collectives", False,
            "debug: block after each collective (FLAGS_sync_nccl_allreduce)")
define_flag("FLAGS_eager_op_cache", True,
            "cache jitted fwd+vjp executables per (op, shapes, dtypes, "
            "attrs) for eager dispatch (reference: the C++ tracer's "
            "microsecond per-op path, imperative/tracer.cc:172); disable "
            "to force per-call jax.vjp re-tracing")
define_flag("FLAGS_compile_cache_dir",
            os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR", ""),
            "persistent XLA compilation cache directory "
            "(jax_compilation_cache_dir): elastic relaunches and serving "
            "cold-starts reuse compiled executables across processes; "
            "hits/misses land in xla_compile_cache_events_total. "
            "Set via PADDLE_TPU_COMPILE_CACHE_DIR or set_flags; empty "
            "disables")
# Pallas kernel autotuner (ops/pallas/autotune.py). The env vars are read
# LIVE by the autotuner and take precedence; these flags are the set_flags-
# able fallback when the env is unset. PADDLE_TPU_AUTOTUNE supports the
# extra value "force" (tune even in interpret mode / on CPU — the CI
# path), which only the env var can express.
define_flag("FLAGS_autotune",
            os.environ.get("PADDLE_TPU_AUTOTUNE", "1").lower() not in
            ("0", "false", "off", "no"),
            "benchmark Pallas kernel block-shape candidates at first real "
            "shape encounter and use the measured winner; off = every "
            "kernel keeps its static default pick (PADDLE_TPU_AUTOTUNE=0)")
define_flag("FLAGS_autotune_cache_dir",
            os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE_DIR", ""),
            "persistent kernel-autotune cache directory: tuned block "
            "configs keyed (op, shape-bucket, dtype, chip) as CRC'd JSON; "
            "a fleet sharing the dir tunes once "
            "(PADDLE_TPU_AUTOTUNE_CACHE_DIR); empty disables persistence")

if os.environ.get("FLAGS_check_nan_inf"):
    _on_flag_set("FLAGS_check_nan_inf", flag("FLAGS_check_nan_inf"))
if flag("FLAGS_debug_nans"):
    _on_flag_set("FLAGS_debug_nans", True)
if flag("FLAGS_compile_cache_dir"):
    _apply_compile_cache_dir(flag("FLAGS_compile_cache_dir"))
