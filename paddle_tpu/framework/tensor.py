"""Eager Tensor.

TPU-native equivalent of the reference's eager tensor
(`/root/reference/paddle/phi/core/dense_tensor.h:38` + pybind eager tensor
`paddle/fluid/pybind/eager.cc`): a thin host object wrapping a `jax.Array`
with paddle semantics — `stop_gradient` (default True for user tensors, False
for parameters), `.grad`, `.backward()`, place/device movement, numpy interop.

Most math methods are attached by `paddle_tpu.ops` at import time (the op
library is a single source of truth shared by eager mode and compiled
programs, mirroring how phi kernels back both dygraph and static graph).
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod
from . import place as place_mod
from . import tape as tape_mod


class RemovableHandle:
    """Unregistration handle for `Tensor.register_hook` (reference
    `python/paddle/fluid/dygraph/varbase_patch_methods.py` TensorHookRemoveHelper)."""

    __slots__ = ("_hooks", "_h")

    def __init__(self, hooks, h):
        self._hooks, self._h = hooks, h

    def remove(self):
        try:
            self._hooks.remove(self._h)
        except ValueError:
            pass


class Tensor:
    __slots__ = ("data", "stop_gradient", "grad", "_node", "name",
                 "persistable", "dist_attr", "_hooks", "__weakref__")

    def __init__(self, data, dtype=None, place=None, stop_gradient: bool = True,
                 name: Optional[str] = None):
        self.dist_attr = None  # set by distributed.shard_tensor
        if isinstance(data, Tensor):
            data = data.data
        if not isinstance(data, jax.Array):
            if dtype is None and isinstance(data, (bool, int, float, list, tuple)):
                # paddle semantics: python floats default to the default dtype
                probe = np.asarray(data)
                if probe.dtype == np.float64:
                    dtype = dtype_mod.get_default_dtype()
                elif probe.dtype == np.int64:
                    dtype = jnp.int64
            data = jnp.asarray(data, dtype=dtype_mod.convert_dtype(dtype))
        elif dtype is not None:
            data = data.astype(dtype_mod.convert_dtype(dtype))
        if place is not None and hasattr(place, "jax_device"):
            data = jax.device_put(data, place.jax_device)
        self.data = data
        self.stop_gradient = bool(stop_gradient)
        self.grad: Optional[Tensor] = None
        self._node = None          # producing tape Node (None => leaf)
        self.name = name
        self.persistable = False
        self._hooks = None         # gradient hooks (lazy; see register_hook)

    # -- metadata -----------------------------------------------------------
    @property
    def shape(self):
        return list(self.data.shape)

    @property
    def ndim(self):
        return self.data.ndim

    # paddle's Tensor.size is an int (numel)
    @property
    def size(self):
        return int(np.prod(self.data.shape)) if self.data.ndim else 1

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def place(self):
        try:
            dev = self.data.devices().pop()
        except Exception:
            return place_mod.CPUPlace()
        if place_mod._platform_of(dev) == "cpu":
            return place_mod.CPUPlace()
        return place_mod.TPUPlace(dev.id)

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from .. import ops
        return ops.transpose(self, list(range(self.ndim))[::-1])

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.data.shape[0]

    def __repr__(self):
        return (f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}, "
                f"stop_gradient={self.stop_gradient},\n       {np.asarray(self.data)!r})")

    # -- conversion ---------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def __array__(self, dtype=None):
        a = np.asarray(self.data)
        return a.astype(dtype) if dtype is not None else a

    # lets jnp.* consume Tensor directly
    def __jax_array__(self):
        return self.data

    def item(self, *args):
        return self.data.item(*args) if args else self.data.item()

    def tolist(self):
        return np.asarray(self.data).tolist()

    def astype(self, dtype):
        from .. import ops
        return ops.cast(self, dtype)

    cast = astype

    def detach(self) -> "Tensor":
        t = Tensor(self.data, stop_gradient=True)
        t.name = self.name
        return t

    def clone(self) -> "Tensor":
        from .. import ops
        return ops.assign(self)

    def to(self, device=None, dtype=None, blocking=True):
        data = self.data
        if device is not None:
            if isinstance(device, place_mod.Place):
                p = device
            else:
                name, _, idx = str(device).partition(":")
                idx = int(idx) if idx else 0
                p = place_mod.CPUPlace() if name == "cpu" else place_mod.TPUPlace(idx)
            data = jax.device_put(data, p.jax_device)
        if dtype is not None:
            data = data.astype(dtype_mod.convert_dtype(dtype))
        t = Tensor(data, stop_gradient=self.stop_gradient)
        t.name = self.name
        return t

    def cpu(self):
        return self.to("cpu")

    def tpu(self, idx=0):
        return self.to(f"tpu:{idx}")

    cuda = tpu

    def pin_memory(self):
        return self

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False,
                 create_graph: bool = False):
        tape_mod.backward([self], [grad_tensor], retain_graph=retain_graph,
                          create_graph=create_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad.data), stop_gradient=True)
        else:
            self.grad = None

    def register_hook(self, hook):
        """Register a gradient hook (`varbase_patch_methods.py:258` /
        `imperative/gradient_accumulator.cc` hook semantics): called with
        this tensor's fully-accumulated gradient during `backward()`; a
        non-None return value replaces the gradient (both what propagates
        upstream and, for leaves, what lands in `.grad`). Returns a handle
        whose `remove()` unregisters the hook."""
        if not callable(hook):
            raise TypeError(f"hook must be callable, got {type(hook)}")
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)
        return RemovableHandle(self._hooks, hook)

    # -- mutation (rebinds the underlying array; used by optimizers etc.) ---
    def _rebind_(self, other: "Tensor"):
        """Assign another tensor's value AND autograd node to self (view-update)."""
        self.data = other.data
        self._node = other._node
        if other._node is not None:
            # the node tracked `other`; re-point its output weakref to self
            import weakref
            node = other._node
            node.outputs = [weakref.ref(self) if r() is other else r
                            for r in node.outputs]
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value.data
        self.data = jnp.asarray(value, dtype=self.data.dtype).reshape(self.data.shape)
        return self

    def fill_(self, value):
        self.data = jnp.full_like(self.data, value)
        return self

    def zero_(self):
        self.data = jnp.zeros_like(self.data)
        return self

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        from .. import ops
        return ops.getitem(self, idx)

    def __setitem__(self, idx, value):
        from .. import ops
        self._rebind_(ops.setitem(self, idx, value))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- operators: filled in by paddle_tpu.ops via _attach_method ----------
    def __bool__(self):
        return bool(self.data)

    def __int__(self):
        return int(self.data)

    def __float__(self):
        return float(self.data)

    def __index__(self):
        return int(self.data)

    def __hash__(self):
        return id(self)


def _attach_method(name, fn):
    """Attachment hook used by paddle_tpu.ops to install tensor methods."""
    setattr(Tensor, name, fn)


# `register_pytree_node`: Tensors flow through jax transforms as their arrays.
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t.data,), (t.stop_gradient,)),
    lambda aux, children: Tensor(children[0], stop_gradient=aux[0]),
)
