"""Core framework: Tensor, Parameter, autograd tape, dtype/place/random, IO."""
from . import dtype, place, random, tape  # noqa: F401
from .io import load, save  # noqa: F401
from .param import Parameter  # noqa: F401
from .tensor import Tensor  # noqa: F401
