"""Dtype registry and default-dtype policy.

TPU-native equivalent of the reference's dtype plumbing
(`/root/reference/paddle/phi/common/data_type.h`,
`python/paddle/framework/dtype.py`): every paddle dtype maps onto a JAX/numpy
dtype. bfloat16 is first-class (the TPU MXU native format).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (jnp dtypes are numpy dtype instances).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

# `paddle.dtype` class alias (dtypes here ARE numpy dtypes)
dtype = jnp.dtype

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "fp16": float16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_default_dtype = jnp.float32


def convert_dtype(dtype):
    """Normalize a user-supplied dtype (str / np.dtype / jnp dtype) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise ValueError(f"unknown dtype {dtype!r}")
        return jnp.dtype(_STR2DTYPE[dtype])
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    """Canonical string name for a dtype ('float32', 'bfloat16', ...)."""
    return jnp.dtype(dtype).name


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if d not in (jnp.dtype(jnp.float16), jnp.dtype(jnp.bfloat16),
                 jnp.dtype(jnp.float32), jnp.dtype(jnp.float64)):
        raise TypeError(f"default dtype must be a float type, got {d}")
    _default_dtype = d


def get_default_dtype():
    return jnp.dtype(_default_dtype)


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


def promote_types(a, b):
    return jnp.promote_types(a, b)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype))


def iinfo(dtype):
    return np.iinfo(convert_dtype(dtype))
