"""Parameter — trainable leaf tensor.

Reference: `EagerParamBase` (`/root/reference/python/paddle/fluid/framework.py:6518`).
"""
from __future__ import annotations

from .tensor import Tensor


class Parameter(Tensor):
    __slots__ = ("trainable", "regularizer", "need_clip", "optimize_attr",
                 "is_distributed", "dist_spec")

    def __init__(self, data, dtype=None, name=None, trainable: bool = True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.regularizer = None
        self.need_clip = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.is_distributed = False
        self.dist_spec = None  # PartitionSpec for the hybrid-parallel engine
        self.persistable = True

    @property
    def trainable_(self):
        return self.trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
