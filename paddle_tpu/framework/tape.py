"""Eager autograd tape.

TPU-native replacement for the reference's dygraph autograd engine
(`/root/reference/paddle/fluid/eager/backward.cc:521` `RunBackward`,
`imperative/basic_engine.cc:391`): instead of per-op C++ GradNodes, every
differentiable eager op records a `jax.vjp` closure on a thread-local tape.
`backward()` walks the tape in reverse creation order (already a topological
order for an eager program) and accumulates cotangents — the JAX residuals
play the role of the reference's `TensorWrapper` saved tensors.

Inside `jit`-compiled functions the tape is irrelevant: compiled training steps
differentiate functionally with `jax.grad`/`jax.vjp` directly.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

_tls = threading.local()


def _state():
    if not hasattr(_tls, "tape"):
        _tls.tape = []
        _tls.grad_enabled = True
    return _tls


class Node:
    """One recorded differentiable op: cotangents flow outputs -> inputs.

    `prim_fn`/`in_arrs` (when recorded) hold the replayable primal — the
    pure tuple-returning impl and its primal input arrays — which is what
    makes `create_graph=True` possible: double grad re-linearizes the op
    through a fresh `jax.vjp` executed AS a recorded op, so the produced
    gradients stay on-tape (the reference keeps the analogous
    re-executable grad graph in `partial_grad_engine.cc` / eager
    `GeneralGrad`, `/root/reference/paddle/fluid/eager/backward.cc:421`).
    """

    __slots__ = ("vjp_fn", "inputs", "outputs", "out_meta", "name",
                 "released", "prim_fn", "in_arrs")

    def __init__(self, vjp_fn: Callable, inputs: Sequence[Any], outputs: Sequence[Any],
                 out_meta: Sequence[tuple], name: str,
                 prim_fn: Optional[Callable] = None,
                 in_arrs: Optional[tuple] = None):
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)    # Tensor objects (kept alive for accumulation)
        # weak refs: a dead output can never receive a cotangent (all consumers
        # hold strong input refs), and weakness lets all-dead nodes be pruned;
        # id() of a dead object is never consulted, so CPython id reuse is safe
        self.outputs = [weakref.ref(o) for o in outputs]
        self.out_meta = list(out_meta)  # (shape, dtype) per output, for zero cotangents
        self.name = name
        self.released = False
        self.prim_fn = prim_fn
        self.in_arrs = in_arrs

    @property
    def out_ids(self):
        """ids of live outputs; dead outputs yield a non-matching sentinel."""
        return [id(o) if (o := ref()) is not None else -1 - i
                for i, ref in enumerate(self.outputs)]

    def all_outputs_dead(self):
        return all(ref() is None for ref in self.outputs)


def grad_enabled() -> bool:
    return _state().grad_enabled


class no_grad:
    """Context manager & decorator, `paddle.no_grad` equivalent."""

    def __enter__(self):
        st = _state()
        self._prev = st.grad_enabled
        st.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state().grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        return wrapper


class enable_grad:
    def __enter__(self):
        st = _state()
        self._prev = st.grad_enabled
        st.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _state().grad_enabled = self._prev
        return False


_PRUNE_INTERVAL = 2048


def record(vjp_fn, inputs, outputs, name="op", prim_fn=None,
           in_arrs=None) -> Node:
    node = Node(vjp_fn, inputs, outputs,
                [(o.data.shape, o.data.dtype) for o in outputs], name,
                prim_fn=prim_fn, in_arrs=in_arrs)
    st = _state()
    st.tape.append(node)
    for o in outputs:
        o._node = node
    # periodic GC: nodes whose outputs are all dead cannot propagate anything
    if len(st.tape) % _PRUNE_INTERVAL == 0:
        st.tape = [n for n in st.tape
                   if not (n.released or n.all_outputs_dead())]
    return node


def tape_size() -> int:
    return len(_state().tape)


def reset_tape():
    _state().tape = []


def _fire_hooks(tensor, v, create_graph):
    """Run a tensor's gradient hooks over cotangent `v` (array or Tensor);
    a hook's non-None return replaces the gradient (reference
    `varbase_patch_methods.py:258` semantics)."""
    from .tensor import Tensor

    for h in list(tensor._hooks or ()):
        arg = v if isinstance(v, Tensor) else Tensor(v, stop_gradient=True)
        r = h(arg)
        if r is None:
            continue
        if create_graph:
            v = r if isinstance(r, Tensor) else Tensor(jnp.asarray(r))
        else:
            v = r.data if isinstance(r, Tensor) else jnp.asarray(r)
    return v


def _relinearize(node, cots):
    """create_graph path: recompute the node's vjp as a RECORDED op.

    Running `jax.vjp(prim_fn, *primals)[1](cots)` through the eager
    dispatcher makes the produced gradients functions-on-tape of both the
    primal inputs and the cotangents, which is exactly what grad-of-grad
    needs (reference: `GeneralGrad`, eager/backward.cc:421).
    """
    from ..ops import _dispatch
    from . import dtype as dtype_mod

    if node.prim_fn is None or node.in_arrs is None:
        raise NotImplementedError(
            f"create_graph through op '{node.name}' is unsupported: the node "
            "records only an opaque vjp (PyLayer / custom native op). Use "
            "paddle_tpu.autograd functional transforms for this op.")
    n_in = len(node.in_arrs)
    diff_idx = tuple(i for i, a in enumerate(node.in_arrs)
                     if dtype_mod.is_floating(a.dtype)
                     or dtype_mod.is_complex(a.dtype))
    prim_fn = node.prim_fn

    def vjp_call(*args):
        prim_ins, cots_ = args[:n_in], args[n_in:]
        outs_, f_vjp = jax.vjp(prim_fn, *prim_ins)
        gs = f_vjp(tuple(c.astype(o.dtype) for c, o in zip(cots_, outs_)))
        return tuple(gs[i] for i in diff_idx)

    # tape connectivity routes through the LIVE input Tensors, but the replay
    # VALUES are the recorded primal arrays: a parameter whose .data was
    # rebound (optimizer step) between forward and this create_graph backward
    # must not silently change the double-grad linearization point
    prim_inputs = [t if t is not None else a
                   for t, a in zip(node.inputs, node.in_arrs)]
    outs = _dispatch.call(vjp_call, [*prim_inputs, *cots],
                          name=f"{node.name}_grad",
                          override_arrs=node.in_arrs)
    outs = outs if isinstance(outs, tuple) else (outs,)
    full = [None] * n_in
    for i, g in zip(diff_idx, outs):
        full[i] = g
    return full


def _engine(outputs, grad_outputs, *, retain_graph, create_graph,
            want=None):
    """Shared reverse traversal for `backward` (want=None: writes leaf
    `.grad`s) and `grad` (want=inputs: harvests and returns gradients).

    Mirrors `egr::Backward`/`GeneralGrad`
    (`/root/reference/paddle/fluid/eager/backward.cc:521,421`): seed with
    ones (or grad_outputs), walk nodes in reverse creation order (already
    topological for an eager program), accumulate fan-in, fire gradient
    hooks on each tensor's fully-accumulated cotangent. With
    `create_graph=True`, cotangents are Tensors and every vjp runs as a
    recorded op (`_relinearize`), so results stay differentiable.
    """
    from .tensor import Tensor

    cg = create_graph
    if cg:
        retain_graph = True

    def as_val(g):
        if cg:
            return g if isinstance(g, Tensor) else Tensor(jnp.asarray(g),
                                                          stop_gradient=True)
        return g.data if isinstance(g, Tensor) else jnp.asarray(g)

    def cast_val(v, dt):
        cur = v.data.dtype if cg else v.dtype
        return v if cur == dt else v.astype(dt)  # Tensor.astype is recorded

    def zeros_val(shape, dt):
        z = jnp.zeros(shape, dt)
        return Tensor(z, stop_gradient=True) if cg else z

    grads: dict[int, Any] = {}
    for t, g in zip(outputs, grad_outputs):
        v = as_val(jnp.ones_like(t.data)) if g is None else as_val(g)
        grads[id(t)] = v if id(t) not in grads else grads[id(t)] + v

    want_map = {id(t): i for i, t in enumerate(want)} if want is not None \
        else {}
    results = [None] * len(want) if want is not None else None
    leaf_acc: dict[int, list] = {}  # id -> [tensor, accumulated value]

    def leaf_add(t, v):
        key = id(t)
        if key in leaf_acc:
            leaf_acc[key][1] = leaf_acc[key][1] + v
        else:
            leaf_acc[key] = [t, v]

    tape: List[Node] = _state().tape
    for node in reversed(tape):
        if node.released:
            continue
        oids = node.out_ids
        if not any(oid in grads for oid in oids):
            continue
        out_vals = []
        for i, (oid, m) in enumerate(zip(oids, node.out_meta)):
            if oid in grads:
                v = grads.pop(oid)
                live = node.outputs[i]()
                if live is not None and live._hooks:
                    # fan-in for this tensor is complete exactly when its
                    # producing node is reached (consumers were created
                    # later, hence already traversed) — the right moment
                    # for accumulated-gradient hooks
                    v = _fire_hooks(live, v, cg)
                v = cast_val(v, m[1])
                if oid in want_map:  # harvest the post-hook total
                    j = want_map[oid]
                    results[j] = v if results[j] is None else results[j] + v
            else:
                v = zeros_val(m[0], m[1])
            out_vals.append(v)
        if cg:
            in_grads = _relinearize(node, tuple(out_vals))
        else:
            in_grads = node.vjp_fn(tuple(out_vals))
        for inp, g in zip(node.inputs, in_grads):
            if g is None or inp is None or inp.stop_gradient:
                continue
            if (not cg) and g.dtype == jax.dtypes.float0:
                continue  # int/bool inputs have no cotangent
            key = id(inp)
            if inp._node is None:
                if want is None or key in want_map:
                    leaf_add(inp, g)
            else:
                grads[key] = g if key not in grads else grads[key] + g
        if not retain_graph:
            node.vjp_fn = None
            node.prim_fn = None
            node.in_arrs = None
            node.released = True

    # seeds that are themselves leaves were never popped (no producing node)
    for t in outputs:
        key = id(t)
        if key in grads and t._node is None and not t.stop_gradient:
            if want is None or key in want_map:
                leaf_add(t, grads.pop(key))

    # finalize leaves: hooks fire on the TOTAL accumulated gradient
    for key, (t, v) in leaf_acc.items():
        if t._hooks:
            v = _fire_hooks(t, v, cg)
        if want is None:
            _accum_leaf(t, v, cg)
        else:
            j = want_map[key]
            results[j] = v if results[j] is None else results[j] + v

    if want is not None:
        # harvest residues that never reached a producing node: non-leaf
        # seeds, and requested inputs whose producer was already released
        # from the tape (their fan-in accumulated in `grads` but no pop
        # point exists any more)
        for t in want:
            key = id(t)
            if key in grads:
                j = want_map[key]
                v = grads.pop(key)
                results[j] = v if results[j] is None else results[j] + v

    if not retain_graph:
        # free only the traversed subgraph; unrelated graphs stay intact
        _state().tape = [n for n in _state().tape if not n.released]
    return results


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             create_graph: bool = False):
    """Reverse-accumulate gradients from `tensors` into leaf `.grad`s.

    Mirrors `egr::Backward` (`/root/reference/paddle/fluid/eager/backward.cc:794`).
    With `create_graph=True` the written `.grad`s are themselves on-tape
    (differentiable), enabling double-grad training recipes.
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    # one host span for the whole reverse sweep (the engine calls recorded
    # vjp closures directly, so it has no per-op dispatch to hook)
    from ..profiler.utils import RecordEvent, TracerEventType
    with RecordEvent("backward", TracerEventType.Backward):
        _engine(tensors, grad_tensors, retain_graph=retain_graph,
                create_graph=create_graph)


def _accum_leaf(tensor, g, create_graph: bool = False):
    from .tensor import Tensor

    if create_graph:
        gt = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g))
        if gt.data.dtype != tensor.data.dtype:
            gt = gt.astype(tensor.data.dtype)  # recorded cast: stays on-tape
        tensor.grad = gt if tensor.grad is None else tensor.grad + gt
        return
    g = g.data if hasattr(g, "data") else jnp.asarray(g)
    if g.dtype != tensor.data.dtype:
        g = g.astype(tensor.data.dtype)
    if tensor.grad is None:
        tensor.grad = Tensor(g, stop_gradient=True)
    else:
        tensor.grad = Tensor(tensor.grad.data + g, stop_gradient=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """`paddle.grad` — gradients of outputs w.r.t. selected inputs (no
    `.grad` side effects).

    Reference: `GeneralGrad` in
    `/root/reference/paddle/fluid/eager/backward.cc:421`. With
    `create_graph=True` the returned gradients are on-tape, so a loss built
    from them (e.g. a WGAN-GP gradient penalty) backpropagates correctly
    through the double grad.
    """
    from .tensor import Tensor

    if retain_graph is None:
        retain_graph = create_graph
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    results = _engine(outputs, grad_outputs, retain_graph=retain_graph,
                      create_graph=create_graph, want=inputs)

    out = []
    for i, g in enumerate(results):
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input {i} is unreachable from outputs (set allow_unused=True)")
            out.append(None)
        elif create_graph:
            out.append(g if isinstance(g, Tensor) else Tensor(g))
        else:
            out.append(Tensor(g, stop_gradient=True))
    return out
