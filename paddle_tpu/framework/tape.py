"""Eager autograd tape.

TPU-native replacement for the reference's dygraph autograd engine
(`/root/reference/paddle/fluid/eager/backward.cc:521` `RunBackward`,
`imperative/basic_engine.cc:391`): instead of per-op C++ GradNodes, every
differentiable eager op records a `jax.vjp` closure on a thread-local tape.
`backward()` walks the tape in reverse creation order (already a topological
order for an eager program) and accumulates cotangents — the JAX residuals
play the role of the reference's `TensorWrapper` saved tensors.

Inside `jit`-compiled functions the tape is irrelevant: compiled training steps
differentiate functionally with `jax.grad`/`jax.vjp` directly.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

_tls = threading.local()


def _state():
    if not hasattr(_tls, "tape"):
        _tls.tape = []
        _tls.grad_enabled = True
    return _tls


class Node:
    """One recorded differentiable op: cotangents flow outputs -> inputs."""

    __slots__ = ("vjp_fn", "inputs", "outputs", "out_meta", "name", "released")

    def __init__(self, vjp_fn: Callable, inputs: Sequence[Any], outputs: Sequence[Any],
                 out_meta: Sequence[tuple], name: str):
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)    # Tensor objects (kept alive for accumulation)
        # weak refs: a dead output can never receive a cotangent (all consumers
        # hold strong input refs), and weakness lets all-dead nodes be pruned;
        # id() of a dead object is never consulted, so CPython id reuse is safe
        self.outputs = [weakref.ref(o) for o in outputs]
        self.out_meta = list(out_meta)  # (shape, dtype) per output, for zero cotangents
        self.name = name
        self.released = False

    @property
    def out_ids(self):
        """ids of live outputs; dead outputs yield a non-matching sentinel."""
        return [id(o) if (o := ref()) is not None else -1 - i
                for i, ref in enumerate(self.outputs)]

    def all_outputs_dead(self):
        return all(ref() is None for ref in self.outputs)


def grad_enabled() -> bool:
    return _state().grad_enabled


class no_grad:
    """Context manager & decorator, `paddle.no_grad` equivalent."""

    def __enter__(self):
        st = _state()
        self._prev = st.grad_enabled
        st.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state().grad_enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        return wrapper


class enable_grad:
    def __enter__(self):
        st = _state()
        self._prev = st.grad_enabled
        st.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _state().grad_enabled = self._prev
        return False


_PRUNE_INTERVAL = 2048


def record(vjp_fn, inputs, outputs, name="op") -> Node:
    node = Node(vjp_fn, inputs, outputs,
                [(o.data.shape, o.data.dtype) for o in outputs], name)
    st = _state()
    st.tape.append(node)
    for o in outputs:
        o._node = node
    # periodic GC: nodes whose outputs are all dead cannot propagate anything
    if len(st.tape) % _PRUNE_INTERVAL == 0:
        st.tape = [n for n in st.tape
                   if not (n.released or n.all_outputs_dead())]
    return node


def tape_size() -> int:
    return len(_state().tape)


def reset_tape():
    _state().tape = []


def backward(tensors, grad_tensors=None, retain_graph: bool = False):
    """Reverse-accumulate gradients from `tensors` into leaf `.grad`s.

    Mirrors `egr::Backward` (`/root/reference/paddle/fluid/eager/backward.cc:794`):
    seeds with ones (or `grad_tensors`), walks nodes in reverse, accumulates
    fan-in, and stores into leaves whose `stop_gradient` is False.
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    grads: dict[int, jax.Array] = {}
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_arr = jnp.ones_like(t.data)
        else:
            g_arr = g.data if isinstance(g, Tensor) else jnp.asarray(g)
        grads[id(t)] = grads.get(id(t), 0) + g_arr

    tape: List[Node] = _state().tape
    # Nodes already form a topological order by construction time.
    for node in reversed(tape):
        if node.released:
            continue
        oids = node.out_ids
        if not any(oid in grads for oid in oids):
            continue
        # vjp_fn expects a concrete cotangent (of the recorded dtype — AMP can
        # mix bf16/fp32 across op boundaries) for every output
        out_grads = tuple(
            grads.pop(oid).astype(m[1]) if oid in grads else jnp.zeros(m[0], m[1])
            for oid, m in zip(oids, node.out_meta)
        )
        in_grads = node.vjp_fn(out_grads)
        for inp, g in zip(node.inputs, in_grads):
            if g is None or inp is None:
                continue
            if inp.stop_gradient:
                continue
            if inp._node is None:  # leaf: accumulate into .grad
                _accum_leaf(inp, g)
            else:
                key = id(inp)
                grads[key] = g if key not in grads else grads[key] + g
        if not retain_graph:
            node.vjp_fn = None
            node.released = True

    # remaining seeds that were themselves leaves
    for t in tensors:
        if id(t) in grads and t._node is None and not t.stop_gradient:
            _accum_leaf(t, grads.pop(id(t)))

    if not retain_graph:
        # free only the traversed subgraph; unrelated graphs stay intact
        _state().tape = [n for n in tape if not n.released]


def _accum_leaf(tensor, g: jax.Array):
    from .tensor import Tensor

    g = jnp.asarray(g)
    if g.dtype != tensor.data.dtype:
        g = g.astype(tensor.data.dtype)
    if tensor.grad is None:
        tensor.grad = Tensor(g, stop_gradient=True)
    else:
        tensor.grad = Tensor(tensor.grad.data + g, stop_gradient=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """`paddle.grad` — gradients of outputs w.r.t. selected inputs (no .grad side effects).

    Reference: `GeneralGrad` in `/root/reference/paddle/fluid/eager/backward.cc:421`.
    Eager-tape implementation: runs the same traversal but harvests cotangents
    for `inputs` instead of writing leaf grads. `create_graph` (double grad) is
    not supported on the eager tape — use `paddle_tpu.autograd.vjp`/`jvp`
    functional APIs for higher-order gradients.
    """
    from .tensor import Tensor

    if create_graph:
        raise NotImplementedError(
            "create_graph on the eager tape is unsupported; use"
            " paddle_tpu.autograd functional transforms for higher-order grad")
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    grads: dict[int, jax.Array] = {}
    for t, g in zip(outputs, grad_outputs):
        g_arr = jnp.ones_like(t.data) if g is None else (
            g.data if isinstance(g, Tensor) else jnp.asarray(g))
        grads[id(t)] = grads.get(id(t), 0) + g_arr

    want = {id(t): i for i, t in enumerate(inputs)}
    results: list[Optional[jax.Array]] = [None] * len(inputs)

    tape: List[Node] = _state().tape
    for node in reversed(tape):
        oids = node.out_ids
        if node.released or not any(oid in grads for oid in oids):
            continue
        out_grads = tuple(
            grads.pop(oid).astype(m[1]) if oid in grads else jnp.zeros(m[0], m[1])
            for oid, m in zip(oids, node.out_meta)
        )
        in_grads = node.vjp_fn(out_grads)
        for inp, g in zip(node.inputs, in_grads):
            if g is None or inp is None or inp.stop_gradient:
                continue
            key = id(inp)
            if key in want:
                i = want[key]
                results[i] = g if results[i] is None else results[i] + g
            if inp._node is not None:
                grads[key] = g if key not in grads else grads[key] + g
        if not retain_graph:
            node.vjp_fn = None
            node.released = True

    for t in outputs:  # an output that is itself a requested input
        if id(t) in want and id(t) in grads:
            i = want[id(t)]
            g = grads[id(t)]
            results[i] = g if results[i] is None else results[i] + g

    out = []
    for i, (t, g) in enumerate(zip(inputs, results)):
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input {i} is unreachable from outputs (set allow_unused=True)")
            out.append(None)
        else:
            out.append(Tensor(g, stop_gradient=True))
    if not retain_graph:
        _state().tape = [n for n in tape if not n.released]
    return out
