"""Global RNG state.

TPU-native equivalent of the reference generator
(`/root/reference/paddle/phi/core/generator.cc`, `python/paddle/fluid/framework.py`
`_set_random_seed`): a process-global functional PRNG built on `jax.random`.

Two regimes:
- **eager**: each stochastic op pulls a fresh subkey from the global generator
  (splitting mutates host-side state).
- **traced** (inside `jit`): host-side mutation would bake one constant key into
  the compiled program, so stochastic ops instead fold a per-trace call counter
  into a *scoped* key supplied by the training loop (`rng_scope`). This is the
  JAX-idiomatic replacement for the reference's per-kernel curand states.
"""
from __future__ import annotations

import contextlib
import os
import threading

import jax
import numpy as np

_prng_picked = False


def _pick_prng_impl():
    """One-time PRNG implementation choice, deferred to first key use.

    On TPU the counter-based `rbg` generator is the default: dropout-mask
    generation with jax's threefry2x32 costs more than the surrounding
    matmuls (measured: BERT-Base b128 train step 182ms -> 108ms switching
    to rbg), and the reference's curand Philox
    (`phi/core/generator.cc` streams) is the same generator class — which
    also means platform-dependent random streams are precedented (the
    reference's CPU and GPU streams differ too). CPU keeps jax's default
    threefry so host runs stay reproducible against history. Override
    either way with PADDLE_TPU_PRNG=rbg|threefry2x32. Deferred because it
    needs the backend platform, and backend init at import time can hang
    on a wedged chip (the round-3 incident)."""
    global _prng_picked
    if _prng_picked:
        return
    _prng_picked = True
    impl = os.environ.get("PADDLE_TPU_PRNG")
    if impl is None:
        try:
            impl = ("rbg" if jax.devices()[0].platform in ("tpu", "axon")
                    else None)
        except Exception:
            impl = None
    if impl:
        try:
            jax.config.update("jax_default_prng_impl", impl)
        except Exception:
            pass  # unknown impl name: keep jax's default


class Generator:
    """Splittable PRNG state, `paddle.fluid.core.default_cpu_generator` equivalent.

    The key is materialized lazily: constructing a Generator (which happens at
    `import paddle_tpu` for the process-global default) must NOT touch jax,
    because `jax.random.PRNGKey` initializes the backend — and on a machine
    where the TPU is wedged that turns a mere import into an indefinite hang
    (observed: leaked subprocess children binding the chip for 21h).
    """

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._lazy_key = None

    @property
    def _key(self):
        if self._lazy_key is None:
            _pick_prng_impl()
            self._lazy_key = jax.random.PRNGKey(self._seed)
        return self._lazy_key

    @_key.setter
    def _key(self, value):
        self._lazy_key = value

    def manual_seed(self, s: int):
        self._seed = int(s)
        self._lazy_key = None
        return self

    def initial_seed(self) -> int:
        return self._seed

    def split(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        return np.asarray(self._key)

    def set_state(self, state):
        import jax.numpy as jnp
        self._key = jnp.asarray(state, dtype=jnp.uint32)


_default_generator = Generator(0)

_tls = threading.local()


def seed(s: int):
    """paddle.seed — reseed the global generator (and numpy for data pipelines)."""
    _default_generator.manual_seed(s)
    return _default_generator


def default_generator() -> Generator:
    return _default_generator


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


@contextlib.contextmanager
def rng_scope(key: jax.Array):
    """Supply a (possibly traced) base key for stochastic ops in this scope.

    Inside the scope, `next_key()` deterministically folds an incrementing
    counter into `key`, so a jitted step function that takes `key` as an
    argument gets fresh randomness every step.
    """
    prev = getattr(_tls, "scope", None)
    _tls.scope = [key, 0]
    try:
        yield
    finally:
        _tls.scope = prev


def in_rng_scope() -> bool:
    return getattr(_tls, "scope", None) is not None


def next_key() -> jax.Array:
    """Fresh PRNG key for one stochastic op (dropout, random init, ...)."""
    scope = getattr(_tls, "scope", None)
    if scope is not None:
        key = jax.random.fold_in(scope[0], scope[1])
        scope[1] += 1
        return key
    return _default_generator.split()
