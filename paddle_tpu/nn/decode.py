"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference: `python/paddle/nn/decode.py` (BeamSearchDecoder over RNN cells,
dynamic_decode loop). The decode loop runs eagerly (python while) over the
compiled cell step — decode lengths are data-dependent, exactly the case
XLA's static shapes push to the host; each step's compute is still jitted
through the normal op dispatch.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..ops import _dispatch as _d
from .layer import Layer

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers -------------------------------------------------------------
    def _merge(self, t):
        """[B, beam, ...] -> [B*beam, ...]"""
        arr = t.data if isinstance(t, Tensor) else t
        return Tensor(arr.reshape((-1,) + arr.shape[2:]))

    def _split(self, t, B):
        arr = t.data if isinstance(t, Tensor) else t
        return arr.reshape((B, self.beam_size) + arr.shape[1:])

    def initialize(self, initial_cell_states):
        """Tile encoder states across beams; beam 0 live, others dead."""
        def tile(s):
            arr = s.data if isinstance(s, Tensor) else s
            B = arr.shape[0]
            tiled = jnp.repeat(arr[:, None], self.beam_size, axis=1)
            return Tensor(tiled.reshape((-1,) + arr.shape[1:]))
        states = jax.tree_util.tree_map(
            tile, initial_cell_states,
            is_leaf=lambda x: isinstance(x, Tensor))
        arr0 = jax.tree_util.tree_leaves(states)[0]
        B = arr0.shape[0] // self.beam_size
        ids = np.full((B, self.beam_size), self.start_token, np.int64)
        log_probs = np.full((B, self.beam_size), -1e9, np.float32)
        log_probs[:, 0] = 0.0
        finished = np.zeros((B, self.beam_size), bool)
        return ids, states, log_probs, finished

    def step(self, inputs, states):
        """One cell step over merged [B*beam] inputs."""
        if self.embedding_fn is not None:
            inputs = self.embedding_fn(inputs)
        out, new_states = self.cell(inputs, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states


def dynamic_decode(decoder: BeamSearchDecoder, inits=None, max_step_num=32,
                   **kwargs):
    """Beam-search decode loop (reference decode.py dynamic_decode).

    Returns (ids [B, beam, T], final_scores [B, beam]).
    """
    ids, states, log_probs, finished = decoder.initialize(inits)
    B, K = ids.shape
    end = decoder.end_token
    history = []

    cur_tokens = ids  # [B, K]
    for _t in range(max_step_num):
        merged_in = Tensor(jnp.asarray(cur_tokens.reshape(-1)))
        logits, states = decoder.step(merged_in, states)
        logp = np.asarray(jax.nn.log_softmax(
            logits.data.astype(jnp.float32), axis=-1)).reshape(B, K, -1)
        V = logp.shape[-1]
        # finished beams only extend with end_token at zero cost
        fin_mask = finished[:, :, None]
        step_scores = np.where(fin_mask, -1e9, logp)
        if np.any(finished):
            end_col = np.zeros_like(step_scores[..., end])
            step_scores[..., end] = np.where(finished, end_col,
                                             step_scores[..., end])
        total = log_probs[:, :, None] + step_scores          # [B,K,V]
        flat = total.reshape(B, K * V)
        top_idx = np.argpartition(-flat, K - 1, axis=1)[:, :K]
        # order the K best
        order = np.argsort(-np.take_along_axis(flat, top_idx, axis=1), axis=1)
        top_idx = np.take_along_axis(top_idx, order, axis=1)
        parent = top_idx // V
        token = top_idx % V
        log_probs = np.take_along_axis(flat, top_idx, axis=1)
        finished = np.take_along_axis(finished, parent, axis=1) | \
            (token == end)
        history.append((token.copy(), parent.copy()))
        cur_tokens = token

        if finished.all():
            # every beam has emitted end_token: stop BEFORE the state
            # reorder — the states are dead (no further cell step reads
            # them), and gathering the whole state tree one last time is
            # pure waste for large cells
            break

        # reorder cell states by parent beam (a finished beam's only
        # above-floor candidate is its own end-extension, so its state is
        # gathered from itself — finished hypotheses never inherit a live
        # beam's state)
        def reorder(s):
            arr = s.data if isinstance(s, Tensor) else s
            sp = arr.reshape((B, K) + arr.shape[1:])
            gathered = np.take_along_axis(
                np.asarray(sp),
                parent.reshape((B, K) + (1,) * (sp.ndim - 2)), axis=1)
            return Tensor(jnp.asarray(
                gathered.reshape((-1,) + arr.shape[1:])))
        states = jax.tree_util.tree_map(
            reorder, states, is_leaf=lambda x: isinstance(x, Tensor))

    # backtrace through parents
    T = len(history)
    out = np.zeros((B, K, T), np.int64)
    beam_idx = np.broadcast_to(np.arange(K), (B, K)).copy()
    for t in range(T - 1, -1, -1):
        token, parent = history[t]
        out[:, :, t] = np.take_along_axis(token, beam_idx, axis=1)
        beam_idx = np.take_along_axis(parent, beam_idx, axis=1)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(log_probs))
