"""nn.Layer — module system.

Reference: `Layer` in `/root/reference/python/paddle/fluid/dygraph/layers.py`
(parameters, buffers, hooks, state_dict, train/eval, apply, to). Parameters
are `framework.param.Parameter` leaves; a functional capture utility
(`paddle_tpu.jit.functionalize`) swaps their arrays for traced values so the
same Layer drives both eager mode and compiled training steps.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.param import Parameter
from ..framework.tensor import Tensor
from ..profiler import health as _health_mod


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self._forward_pre_hooks: "collections.OrderedDict[int, Callable]" = collections.OrderedDict()
        self._forward_post_hooks: "collections.OrderedDict[int, Callable]" = collections.OrderedDict()
        self._name_scope = name_scope or type(self).__name__.lower()

    # ---- attribute plumbing ----------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            self._sub_layers.pop(name, None)
            self._buffers.pop(name, None)
            params[name] = value
            object.__setattr__(self, name, value)
            return
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            self._parameters.pop(name, None)
            self._buffers.pop(name, None)
            layers[name] = value
            object.__setattr__(self, name, value)
            return
        bufs = self.__dict__.get("_buffers")
        if bufs is not None and name in bufs:
            if value is None or isinstance(value, Tensor):
                bufs[name] = value
        object.__setattr__(self, name, value)

    def __delattr__(self, name):
        self._parameters.pop(name, None)
        self._sub_layers.pop(name, None)
        self._buffers.pop(name, None)
        object.__delattr__(self, name)

    # ---- construction helpers --------------------------------------------
    def create_parameter(self, shape, dtype=None, attr=None, is_bias=False,
                         default_initializer=None):
        from .initializer import Constant, XavierUniform
        from . import initializer as init_mod
        dtype = dtype or self._dtype
        init = default_initializer
        attr_name = None
        if attr is not None and not isinstance(attr, bool):
            init = getattr(attr, "initializer", None) or init
            attr_name = getattr(attr, "name", None)
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        data = init(tuple(shape), dtype_mod.convert_dtype(dtype))
        p = Parameter(data, name=attr_name)
        return p

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
            object.__setattr__(self, name, None)
        else:
            setattr(self, name, parameter)
        return parameter

    def add_sublayer(self, name, sublayer):
        setattr(self, name, sublayer)
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        object.__setattr__(self, name, tensor)
        return tensor

    # ---- traversal --------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                full = f"{name}.{pname}" if name else pname
                if p.name is None:
                    p.name = full  # stable structured name (used by optimizer
                    # state dicts and per-param weight-decay exclusion)
                yield full, p

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters()]

    def named_buffers(self, prefix="") -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def buffers(self) -> List[Tensor]:
        return [b for _, b in self.named_buffers()]

    def named_sublayers(self, prefix="", include_self=False
                        ) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self._sub_layers.items():
            if l is not None:
                yield l

    def named_children(self):
        yield from ((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ---- mode -------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ---- hooks ------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        h = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook):
        h = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # ---- call -------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        if _health_mod._ATTRIBUTION_ARMED:
            # NaN/Inf attribution armed (FLAGS_check_nan_inf or an
            # eager_replay): keep a thread-local layer stack so the
            # dispatch post-check can name the layer PATH that produced
            # the first bad value. Unarmed cost: one module-attr test.
            _health_mod.push_layer(self)
            try:
                return self._call_impl(*inputs, **kwargs)
            finally:
                _health_mod.pop_layer()
        return self._call_impl(*inputs, **kwargs)

    def _call_impl(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # ---- state dict -------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            layer_name, _, buf_name = name.rpartition(".")
            owner = self
            if layer_name:
                for part in layer_name.split("."):
                    owner = owner._sub_layers.get(part, owner)
            if buf_name in getattr(owner, "_non_persistable_buffer_names", set()):
                continue
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], list(state_dict.keys())
        own = self.state_dict()
        for name, target in own.items():
            if name in state_dict:
                src = state_dict[name]
                arr = src.data if isinstance(src, Tensor) else jnp.asarray(np.asarray(src))
                if tuple(arr.shape) != tuple(target.data.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: got {tuple(arr.shape)}, "
                        f"expected {tuple(target.data.shape)}")
                target.data = arr.astype(target.data.dtype)
                unexpected.remove(name)
            else:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=True):
        import jax
        from ..framework import place as place_mod
        for t in list(self.parameters()) + list(self.buffers()):
            if device is not None:
                name, _, idx = str(device).partition(":")
                idx = int(idx) if idx else 0
                p = place_mod.CPUPlace() if name == "cpu" else place_mod.TPUPlace(idx)
                t.data = jax.device_put(t.data, p.jax_device)
            if dtype is not None and dtype_mod.is_floating(t.data.dtype):
                t.data = t.data.astype(dtype_mod.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
