"""Recurrent layers: SimpleRNN/LSTM/GRU cells and (bi)directional stacks.

Reference: `python/paddle/nn/layer/rnn.py` (RNNCellBase:*, SimpleRNNCell,
LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN/LSTM/GRU multi-layer wrappers) over
the cudnn rnn kernels. TPU translation: the time loop is a `lax.scan` inside
ONE dispatched kernel — compiler-friendly (static trip count, no per-step
python), differentiable through `jax.vjp`, and the whole sequence runs as a
single fused XLA loop instead of cudnn calls.

Gate layouts match the reference (i, f, c, o for LSTM; r, z, c for GRU), so
state dicts port over.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..nn.initializer import Uniform
from ..ops import _dispatch
from .layer import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


# --------------------------- pure cell steps --------------------------------

def _simple_step(x_t, h, wi, wh, bi, bh, act):
    z = x_t @ wi.T + h @ wh.T + bi + bh
    return jnp.tanh(z) if act == "tanh" else jax.nn.relu(z)


def _lstm_step(x_t, h, c, wi, wh, bi, bh):
    z = x_t @ wi.T + h @ wh.T + bi + bh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    return jnp.tanh(c2) * o, c2


def _gru_step(x_t, h, wi, wh, bi, bh):
    xz = x_t @ wi.T + bi
    hz = h @ wh.T + bh
    xr, xu, xc = jnp.split(xz, 3, axis=-1)
    hr, hu, hc = jnp.split(hz, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    u = jax.nn.sigmoid(xu + hu)
    c = jnp.tanh(xc + r * hc)
    return u * h + (1.0 - u) * c


def _reverse_sequence(x, lengths):
    """Per-row reversal of the VALID prefix: out[b,t] = x[b, len_b-1-t] for
    t < len_b, else x[b,t] (padding stays in place)."""
    T = x.shape[1]
    t_idx = jnp.arange(T)[None, :]
    lens = lengths[:, None].astype(jnp.int32)
    src = jnp.where(t_idx < lens, lens - 1 - t_idx, t_idx)
    return jnp.take_along_axis(x, src[:, :, None], axis=1)


def _scan_layer(mode, x, h0, c0, wi, wh, bi, bh, reverse, act, lengths=None):
    """x [B,T,I] -> (outputs [B,T,H], (h_n, c_n)). With `lengths` [B],
    steps past each row's length are masked: the state freezes (final state
    = state at t=len-1) and the padded outputs are zero, matching the
    reference's variable-length semantics; the reverse direction reverses
    only the valid prefix."""
    prefix_reversed = False
    if lengths is not None and reverse:
        x = _reverse_sequence(x, lengths)
        reverse = False  # valid-prefix reversal replaces the plain flip
        prefix_reversed = True
    xt = jnp.swapaxes(x, 0, 1)  # [T,B,I]
    if reverse:
        xt = jnp.flip(xt, axis=0)

    def masked(t, new, old):
        if lengths is None:
            return new
        alive = (t < lengths.astype(jnp.int32))[:, None]
        return jnp.where(alive, new, old)

    ts = jnp.arange(xt.shape[0])
    if mode == "LSTM":
        def step(carry, inp):
            t, x_t = inp
            h, c = carry
            h2, c2 = _lstm_step(x_t, h, c, wi, wh, bi, bh)
            h2, c2 = masked(t, h2, h), masked(t, c2, c)
            return (h2, c2), masked(t, h2, jnp.zeros_like(h2))
        (h_n, c_n), ys = jax.lax.scan(step, (h0, c0), (ts, xt))
    elif mode == "GRU":
        def step(h, inp):
            t, x_t = inp
            h2 = masked(t, _gru_step(x_t, h, wi, wh, bi, bh), h)
            return h2, masked(t, h2, jnp.zeros_like(h2))
        h_n, ys = jax.lax.scan(step, h0, (ts, xt))
        c_n = h_n
    else:
        def step(h, inp):
            t, x_t = inp
            h2 = masked(t, _simple_step(x_t, h, wi, wh, bi, bh, act), h)
            return h2, masked(t, h2, jnp.zeros_like(h2))
        h_n, ys = jax.lax.scan(step, h0, (ts, xt))
        c_n = h_n
    if reverse:
        ys = jnp.flip(ys, axis=0)
    ys = jnp.swapaxes(ys, 0, 1)
    if prefix_reversed:
        # re-align outputs with the ORIGINAL time order
        ys = _reverse_sequence(ys, lengths)
    return ys, h_n, c_n


# ------------------------------- cells --------------------------------------

class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from .. import ops
        B = batch_ref.shape[batch_dim_idx]
        return ops.full([B, self.hidden_size], init_value, dtype=dtype)

    def _make_params(self, input_size, hidden_size, gates):
        k = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-k, k)
        g = gates * hidden_size
        self.weight_ih = self.create_parameter((g, input_size),
                                               default_initializer=init)
        self.weight_hh = self.create_parameter((g, hidden_size),
                                               default_initializer=init)
        self.bias_ih = self.create_parameter((g,), is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter((g,), is_bias=True,
                                             default_initializer=init)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        self._make_params(input_size, hidden_size, gates=1)

    def forward(self, inputs, states=None):
        h = states if states is not None else \
            self.get_initial_states(inputs)
        out = _dispatch.call(
            lambda x, h, wi, wh, bi, bh, act=self.activation:
            _simple_step(x, h, wi, wh, bi, bh, act),
            [inputs, h, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh], name="simple_rnn_cell")
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self._make_params(input_size, hidden_size, gates=4)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states
        h2, c2 = _dispatch.call(
            lambda x, h, c, wi, wh, bi, bh:
            _lstm_step(x, h, c, wi, wh, bi, bh),
            [inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh], name="lstm_cell")
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self._make_params(input_size, hidden_size, gates=3)

    def forward(self, inputs, states=None):
        h = states if states is not None else \
            self.get_initial_states(inputs)
        h2 = _dispatch.call(
            lambda x, h, wi, wh, bi, bh: _gru_step(x, h, wi, wh, bi, bh),
            [inputs, h, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh], name="gru_cell")
        return h2, h2


# ------------------------------ wrappers ------------------------------------

class RNN(Layer):
    """Run a cell over time (reference nn.RNN): scan-compiled."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs
        if self.time_major:
            from ..ops import transpose
            x = transpose(x, [1, 0, 2])
        # the fused lax.scan path hardcodes the builtin cells' gate math —
        # custom/subclassed cells must run through their own forward()
        fused = type(self.cell) in (LSTMCell, GRUCell, SimpleRNNCell)
        if not fused:
            return self._generic_loop(x, initial_states, sequence_length)
        mode = ("LSTM" if isinstance(self.cell, LSTMCell)
                else "GRU" if isinstance(self.cell, GRUCell) else "RNN")
        act = getattr(self.cell, "activation", "tanh")
        B = x.shape[0]
        from ..ops import zeros
        if initial_states is None:
            h0 = zeros([B, self.cell.hidden_size])
            c0 = zeros([B, self.cell.hidden_size])
        elif isinstance(initial_states, (tuple, list)):
            h0, c0 = initial_states
        else:
            h0, c0 = initial_states, initial_states

        tensors = [x, h0, c0, self.cell.weight_ih, self.cell.weight_hh,
                   self.cell.bias_ih, self.cell.bias_hh]
        has_len = sequence_length is not None
        if has_len:
            tensors.append(sequence_length)

        def impl(x, h0, c0, wi, wh, bi, bh, *rest, mode=mode,
                 rev=self.is_reverse, act=act, has_len=has_len):
            lengths = rest[0] if has_len else None
            return _scan_layer(mode, x, h0, c0, wi, wh, bi, bh, rev, act,
                               lengths=lengths)

        ys, h_n, c_n = _dispatch.call(impl, tensors, name="rnn_scan")
        if self.time_major:
            from ..ops import transpose
            ys = transpose(ys, [1, 0, 2])
        final = (h_n, c_n) if mode == "LSTM" else h_n
        return ys, final

    def _generic_loop(self, x, initial_states, sequence_length):
        """Eager per-step loop through cell.forward (custom cells)."""
        if sequence_length is not None:
            raise NotImplementedError(
                "sequence_length with a custom cell is unsupported")
        from ..ops import stack, transpose
        T = int(x.shape[1])
        order = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for t in order:
            step_in = x[:, t]
            if states is None:
                out, states = self.cell(step_in)
            else:
                out, states = self.cell(step_in, states)
            outs[t] = out
        ys = stack(outs, axis=1)
        if self.time_major:
            ys = transpose(ys, [1, 0, 2])
        return ys, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw = states_bw = None
        if initial_states is not None:
            states_fw, states_bw = initial_states
        from ..ops import concat
        y_fw, s_fw = self.rnn_fw(inputs, states_fw)
        y_bw, s_bw = self.rnn_bw(inputs, states_bw)
        return concat([y_fw, y_bw], axis=-1), (s_fw, s_bw)


class _StackedRNN(Layer):
    MODE = "RNN"
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        from .layers_common import LayerList
        cell_cls = {"LSTM": LSTMCell, "GRU": GRUCell}.get(self.MODE,
                                                          SimpleRNNCell)

        def make_cell(in_size):
            if cell_cls is SimpleRNNCell:
                return cell_cls(in_size, hidden_size, activation=activation)
            return cell_cls(in_size, hidden_size)

        self._layers_fw = LayerList()
        self._layers_bw = LayerList()
        width = 2 * hidden_size if self.bidirectional else hidden_size
        for l in range(num_layers):
            in_size = input_size if l == 0 else width
            self._layers_fw.append(RNN(make_cell(in_size),
                                       time_major=False))
            if self.bidirectional:
                self._layers_bw.append(RNN(make_cell(in_size),
                                           is_reverse=True,
                                           time_major=False))

    def _layer_states(self, initial_states, layer, direction):
        """Slice user-provided [num_layers*dirs, B, H] states for one
        (layer, direction) RNN; None if not given."""
        if initial_states is None:
            return None
        dirs = 2 if self.bidirectional else 1
        idx = layer * dirs + direction

        def pick(s):
            return s[idx]
        if self.MODE == "LSTM":
            h, c = initial_states
            return (pick(h), pick(c))
        return pick(initial_states)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops import concat, stack, transpose
        x = inputs
        if self.time_major:
            x = transpose(x, [1, 0, 2])
        h_list, c_list = [], []
        from . import functional as F
        for l in range(self.num_layers):
            y_fw, s_fw = self._layers_fw[l](
                x, self._layer_states(initial_states, l, 0),
                sequence_length=sequence_length)
            if self.bidirectional:
                y_bw, s_bw = self._layers_bw[l](
                    x, self._layer_states(initial_states, l, 1),
                    sequence_length=sequence_length)
                x = concat([y_fw, y_bw], axis=-1)
                for s in (s_fw, s_bw):
                    if self.MODE == "LSTM":
                        h_list.append(s[0]); c_list.append(s[1])
                    else:
                        h_list.append(s)
            else:
                x = y_fw
                if self.MODE == "LSTM":
                    h_list.append(s_fw[0]); c_list.append(s_fw[1])
                else:
                    h_list.append(s_fw)
            if self.dropout and l < self.num_layers - 1 and self.training:
                x = F.dropout(x, self.dropout)
        out = x
        if self.time_major:
            out = transpose(out, [1, 0, 2])
        h_n = stack(h_list, axis=0)
        if self.MODE == "LSTM":
            return out, (h_n, stack(c_list, axis=0))
        return out, h_n


class SimpleRNN(_StackedRNN):
    MODE = "RNN"


class LSTM(_StackedRNN):
    MODE = "LSTM"


class GRU(_StackedRNN):
    MODE = "GRU"
