"""Layer wrappers completing the `paddle.nn` surface (pooling 3D, padding,
unpool, transposed convs, extra norms/losses/misc — reference
`python/paddle/nn/layer/{pooling,common,norm,loss,distance}.py`)."""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import functional as F
from .initializer import Uniform, XavierUniform
from .layer import Layer
from .layers_common import _ConvNd

__all__ = [
    "AvgPool3D", "MaxPool3D", "AdaptiveAvgPool3D", "AdaptiveMaxPool1D",
    "AdaptiveMaxPool3D", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "Pad1D", "Pad3D", "AlphaDropout", "Dropout3D", "InstanceNorm1D",
    "InstanceNorm3D", "SpectralNorm", "Bilinear", "PairwiseDistance",
    "CTCLoss", "HingeEmbeddingLoss", "HSigmoidLoss", "Conv1DTranspose",
    "Conv3DTranspose", "UpsamplingBilinear2D", "UpsamplingNearest2D",
    "Fold",
]


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, ceil_mode, exclusive)

    def forward(self, x):
        k, s, p, cm, ex = self._a
        return F.avg_pool3d(x, k, s, p, cm, ex)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, ceil_mode, return_mask)

    def forward(self, x):
        k, s, p, cm, rm = self._a
        return F.max_pool3d(x, k, s, p, cm, rm)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._os)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._os, self._rm = output_size, return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._os, self._rm)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._os, self._rm = output_size, return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._os, self._rm)


class _MaxUnPoolNd(Layer):
    ND = 2

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, os = self._a
        fn = {1: F.max_unpool1d, 2: F.max_unpool2d, 3: F.max_unpool3d}[self.ND]
        return fn(x, indices, k, s, p, output_size=os)


class MaxUnPool1D(_MaxUnPoolNd):
    ND = 1


class MaxUnPool2D(_MaxUnPoolNd):
    ND = 2


class MaxUnPool3D(_MaxUnPoolNd):
    ND = 3


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self._padding = padding
        self._mode = mode
        self._value = value
        self._fmt = data_format

    def forward(self, x):
        return F.pad(x, self._padding, self._mode, self._value, self._fmt)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        if isinstance(padding, int):
            padding = [padding, padding]
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        if isinstance(padding, int):
            padding = [padding] * 6
        super().__init__(padding, mode, value, data_format)


class AlphaDropout(Layer):
    """reference common.py AlphaDropout (SELU-preserving dropout)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        from ..framework import random as random_mod
        from ..ops import _dispatch as _d

        def impl(a, key, *, p=self.p):
            alpha = 1.6732632423543772
            scale = 1.0507009873554805
            alpha_p = -alpha * scale
            keep = jax.random.bernoulli(key, 1 - p, a.shape)
            # variance-restoring affine (SELU paper): 1/sqrt((1-p)(1+p*a'^2))
            a_mult = (1 - p) * (1 + p * alpha_p ** 2)
            a_coef = a_mult ** -0.5
            b_coef = -a_coef * p * alpha_p
            return a_coef * (jnp.where(keep, a, alpha_p)) + b_coef
        from ..framework.tensor import Tensor
        key = random_mod.default_generator().split()
        return _d.call(impl, [x, Tensor(key)], name="alpha_dropout")


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout3d(x, self.p, self.training)


class _InstanceNormNd(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format=None,
                 name=None):
        super().__init__()
        self._eps = epsilon
        self.scale = self.create_parameter((num_features,))
        self.scale.data = jnp.ones_like(self.scale.data)
        self.bias = self.create_parameter((num_features,), is_bias=True)

    def forward(self, x):
        from ..ops import _dispatch as _d

        def impl(a, w, b, *, eps=self._eps):
            axes = tuple(range(2, a.ndim))
            mean = jnp.mean(a, axis=axes, keepdims=True)
            var = jnp.var(a, axis=axes, keepdims=True)
            xhat = (a - mean) * jax.lax.rsqrt(var + eps)
            shape = (1, -1) + (1,) * (a.ndim - 2)
            return xhat * w.reshape(shape) + b.reshape(shape)
        return _d.call(impl, [x, self.scale, self.bias], name="instance_norm")


class InstanceNorm1D(_InstanceNormNd):
    pass


class InstanceNorm3D(_InstanceNormNd):
    pass


class SpectralNorm(Layer):
    """reference norm.py SpectralNorm: power-iteration spectral norm of a
    weight (as a standalone layer transforming the given weight tensor)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._iters = power_iters
        self._eps = eps
        h = int(weight_shape[dim])
        w = int(np.prod([weight_shape[i] for i in range(len(weight_shape))
                         if i != dim]))
        import numpy.random as npr
        self.weight_u = self.create_parameter((h,))
        self.weight_v = self.create_parameter((w,))
        self.weight_u.data = jnp.asarray(
            npr.default_rng(0).normal(size=(h,)).astype(np.float32))
        self.weight_v.data = jnp.asarray(
            npr.default_rng(1).normal(size=(w,)).astype(np.float32))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ..ops import _dispatch as _d

        def impl(w, u, v, *, dim=self._dim, iters=self._iters, eps=self._eps):
            wm_live = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            wm = jax.lax.stop_gradient(wm_live)  # u/v are non-differentiable
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm_live @ v  # gradient flows through sigma (torch)
            return w / sigma, u, v
        out, u, v = _d.call(impl, [weight, self.weight_u, self.weight_v],
                            name="spectral_norm")
        # persist the power-iteration state: each call refines the estimate
        # (the reference assigns u/v back every forward)
        self.weight_u.data = jax.lax.stop_gradient(u.data)
        self.weight_v.data = jax.lax.stop_gradient(v.data)
        return out


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        k = 1.0 / math.sqrt(in1_features)
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            default_initializer=Uniform(-k, k))
        self.bias = self.create_parameter((out_features,), is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._p, self._eps, self._keep = p, epsilon, keepdim

    def forward(self, x, y):
        from ..ops import _dispatch as _d

        def impl(a, b, *, p=self._p, eps=self._eps, keep=self._keep):
            d = a - b + eps
            return jnp.sum(jnp.abs(d) ** p, axis=-1,
                           keepdims=keep) ** (1.0 / p)
        return _d.call(impl, [x, y], name="pairwise_distance")


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        from ..ops import _dispatch as _d

        def impl(x, y, *, margin=self.margin, reduction=self.reduction):
            loss = jnp.where(y == 1.0, x,
                             jnp.maximum(0.0, margin - x))
            if reduction == "mean":
                return jnp.mean(loss)
            if reduction == "sum":
                return jnp.sum(loss)
            return loss
        return _d.call(impl, [input, label], name="hinge_embedding_loss")


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        k = 1.0 / math.sqrt(feature_size)
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size),
            default_initializer=Uniform(-k, k))
        self.bias = self.create_parameter((num_classes - 1,), is_bias=True)

    def forward(self, input, label):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True)
        self._output_padding = output_padding

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size=output_size)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True)
        self._output_padding = output_padding

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  output_size=output_size)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._size, self._scale = size, scale_factor

    def forward(self, x):
        return F.interpolate(x, size=self._size, scale_factor=self._scale,
                             mode="nearest")


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._size, self._scale = size, scale_factor

    def forward(self, x):
        return F.interpolate(x, size=self._size, scale_factor=self._scale,
                             mode="bilinear", align_corners=True)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self._a)
