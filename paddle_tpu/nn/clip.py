"""Gradient clipping.

Reference: `ClipGradByGlobalNorm` etc. (`/root/reference/python/paddle/fluid/clip.py`).
Clips operate on (param, grad) lists eagerly and have pure functional cores
reused by compiled training steps and the hybrid-parallel optimizer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def clip_fn(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)

    def __call__(self, params_grads):
        return [(p, Tensor(jnp.clip(g.data, self.min, self.max)) if g is not None else None)
                for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g.data * scale).astype(g.data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def clip_fn(self, grads):
        """Pure functional core (pytree of arrays -> pytree of arrays)."""
        leaves = jax.tree_util.tree_leaves(grads)
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gn, 1e-12), 1.0)
        return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), grads)

    def __call__(self, params_grads):
        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        sq = sum(jnp.sum(jnp.square(g.data.astype(jnp.float32))) for g in grads
                 if getattr(g, "data", None) is not None)
        global_norm = jnp.sqrt(sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g.data * scale).astype(g.data.dtype))))
        return out
