"""Common layers.

Reference parity: `python/paddle/nn/layer/` (common.py, conv.py, norm.py,
pooling.py, activation.py, loss.py, container.py).
"""
from __future__ import annotations

import collections
import math

import jax.numpy as jnp
import numpy as np

from . import functional as F
from .initializer import Constant, KaimingUniform, Normal, Uniform, XavierUniform
from .layer import Layer
from ..framework import dtype as dtype_mod
from ..framework.param import Parameter
from ..framework.tensor import Tensor


# ---------------------------------------------------------------------------
# containers
# ---------------------------------------------------------------------------
class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx % len(self) if idx < 0 else idx)]

    def __setitem__(self, idx, layer):
        self.add_sublayer(str(idx), layer)

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for k, v in (sublayers.items() if isinstance(sublayers, dict) else sublayers):
                self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __len__(self):
        return len(self._sub_layers)

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()


# ---------------------------------------------------------------------------
# core layers
# ---------------------------------------------------------------------------
class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if weight_attr is not None else XavierUniform())
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.weight.shape[0]}, out={self.weight.shape[1]}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=None if weight_attr is not None else XavierUniform())
        if padding_idx is not None:
            pi = padding_idx if padding_idx >= 0 else num_embeddings + padding_idx
            self.weight.data = self.weight.data.at[pi].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ..ops import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, scale_factor, mode, align_corners, align_mode, data_format)

    def forward(self, x):
        return F.upsample(x, *self._args)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor)


# ---------------------------------------------------------------------------
# conv layers
# ---------------------------------------------------------------------------
class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * nd
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        if transpose:
            w_shape = (in_channels, out_channels // groups) + tuple(ks)
        else:
            w_shape = (out_channels, in_channels // groups) + tuple(ks)
        fan_in = (in_channels // groups) * int(np.prod(ks))
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=None if weight_attr is not None
            else KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            bound = 1 / math.sqrt(fan_in)
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr,
                default_initializer=Uniform(-bound, bound)
                if bias_attr is None else None, is_bias=True)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True)
        self._output_padding = output_padding

    def forward(self, x):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, self._data_format)


# ---------------------------------------------------------------------------
# normalization layers
# ---------------------------------------------------------------------------
class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(self._normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class RMSNorm(Layer):
    """TPU-friendly RMS norm (not in the reference snapshot; modern-LLM parity)."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter((hidden_size,),
                                            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    """`act="relu"` fuses the activation into the BN kernel
    (Pallas fused BN — reference `fused_bn_activation_op.cu`); calling
    `forward(x, residual)` additionally folds a residual add before the
    activation (`fused_bn_add_activation_op.cu`), so a ResNet block tail
    `relu(bn(conv(x)) + identity)` is one kernel."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None, act=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self._act = act
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter((num_features,), attr=weight_attr,
                                                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x, residual=None):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats,
                            act=self._act, residual=residual)


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


BatchNorm = BatchNorm2D  # legacy fluid alias


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit/GSPMD the batch axis is global, so plain
    BN statistics are already synchronized — eager per-device use falls back
    to local stats (reference: `python/paddle/nn/layer/norm.py` SyncBatchNorm).
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self._parameters["weight"] = None
        else:
            self.weight = self.create_parameter((num_channels,), attr=weight_attr,
                                                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter((num_channels,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight, self.bias = None, None
            self._parameters["weight"] = None
            self._parameters["bias"] = None
        else:
            self.weight = self.create_parameter((num_features,), attr=weight_attr,
                                                default_initializer=Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self._args)


# ---------------------------------------------------------------------------
# pooling layers
# ---------------------------------------------------------------------------
class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, return_mask, data_format)

    def forward(self, x):
        return F.max_pool2d(x, *self._args)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, ceil_mode, exclusive,
                      divisor_override, data_format)

    def forward(self, x):
        return F.avg_pool2d(x, *self._args)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, return_mask, ceil_mode)

    def forward(self, x):
        return F.max_pool1d(x, *self._args)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, exclusive, ceil_mode)

    def forward(self, x):
        return F.avg_pool1d(x, *self._args)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


# ---------------------------------------------------------------------------
# activation layers
# ---------------------------------------------------------------------------
def _act_layer(name, fn_name, **default_kw):
    def __init__(self, name=None, **kw):
        Layer.__init__(self)
        merged = dict(default_kw)
        merged.update(kw)
        self._kw = merged

    def forward(self, x):
        return getattr(F, fn_name)(x, **self._kw)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _act_layer("ReLU", "relu")
ReLU6 = _act_layer("ReLU6", "relu6")
GELU = _act_layer("GELU", "gelu")
Sigmoid = _act_layer("Sigmoid", "sigmoid")
Tanh = _act_layer("Tanh", "tanh")
Softmax = _act_layer("Softmax", "softmax")
LogSoftmax = _act_layer("LogSoftmax", "log_softmax")
Silu = _act_layer("Silu", "silu")
Swish = _act_layer("Swish", "swish")
Mish = _act_layer("Mish", "mish")
LeakyReLU = _act_layer("LeakyReLU", "leaky_relu")
ELU = _act_layer("ELU", "elu")
CELU = _act_layer("CELU", "celu")
SELU = _act_layer("SELU", "selu")
Hardtanh = _act_layer("Hardtanh", "hardtanh")
Hardsigmoid = _act_layer("Hardsigmoid", "hardsigmoid")
Hardswish = _act_layer("Hardswish", "hardswish")
Hardshrink = _act_layer("Hardshrink", "hardshrink")
Softshrink = _act_layer("Softshrink", "softshrink")
Softplus = _act_layer("Softplus", "softplus")
Softsign = _act_layer("Softsign", "softsign")
Tanhshrink = _act_layer("Tanhshrink", "tanhshrink")
LogSigmoid = _act_layer("LogSigmoid", "log_sigmoid")
ThresholdedReLU = _act_layer("ThresholdedReLU", "thresholded_relu")
GLU = _act_layer("GLU", "glu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter((num_parameters,), attr=weight_attr,
                                            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


# ---------------------------------------------------------------------------
# loss layers
# ---------------------------------------------------------------------------
class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self._kw = dict(weight=weight, ignore_index=ignore_index,
                        reduction=reduction, soft_label=soft_label, axis=axis,
                        use_softmax=use_softmax, label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, **self._kw)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self._reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self._reduction, self._delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self._reduction, self._delta)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._kw = dict(weight=weight, ignore_index=ignore_index, reduction=reduction)

    def forward(self, input, label):
        return F.nll_loss(input, label, **self._kw)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self._kw = dict(weight=weight, reduction=reduction)

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, **self._kw)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self._kw = dict(weight=weight, reduction=reduction, pos_weight=pos_weight)

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, **self._kw)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self._reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin, self._reduction)


# ---------------------------------------------------------------------------
# padding layers
# ---------------------------------------------------------------------------
class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self._args = (padding, mode, value, data_format)

    def forward(self, x):
        from ..ops import pad
        return pad(x, self._args[0], self._args[1], self._args[2], self._args[3])


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self._axis, self._eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self._axis, self._eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self._args)
