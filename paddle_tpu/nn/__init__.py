"""paddle_tpu.nn — layers, functional, initializers.

Reference parity: `python/paddle/nn/`.
"""
from .layer import Layer  # noqa: F401
from .layers_common import *  # noqa: F401,F403
from .layers_common import (  # noqa: F401
    Sequential, LayerList, ParameterList, LayerDict, Linear, Embedding,
    Dropout, Flatten, Identity, Conv1D, Conv2D, Conv3D, Conv2DTranspose,
    LayerNorm, RMSNorm, BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
    SyncBatchNorm, GroupNorm, InstanceNorm2D, MaxPool2D, AvgPool2D,
    AdaptiveAvgPool2D, CrossEntropyLoss, MSELoss, L1Loss, ReLU, GELU, Sigmoid,
    Tanh, Softmax,
)
from .transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401

from .rnn import (  # noqa: F401,E402
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,
    LSTM, GRU,
)
from .layers_extra import *  # noqa: F401,F403,E402
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401,E402
