"""Weight initializers.

Reference: `python/paddle/nn/initializer/` + `python/paddle/fluid/initializer.py`.
Initializers are callables `(shape, dtype) -> jax.Array` drawing from the
global generator.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as random_mod


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels (paddle layout: out/in leading dims vary); use receptive field
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(random_mod.next_key(), shape, dtype,
                                  self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.normal(random_mod.next_key(), shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.truncated_normal(
            random_mod.next_key(), -2.0, 2.0, shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(random_mod.next_key(), shape, dtype, -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(random_mod.next_key(), shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(random_mod.next_key(), shape, dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(random_mod.next_key(), shape, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        return self.gain * jax.nn.initializers.orthogonal()(
            random_mod.next_key(), shape, dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        arr = jnp.asarray(np.asarray(self.value), dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign initializer shape {arr.shape} != {shape}"
        return arr


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        out = np.zeros(shape, dtype=np.float32)
        o, i = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for c in range(min(o // self.groups, i)):
                idx = (g * (o // self.groups) + c, c) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype)


# paddle.ParamAttr equivalent
class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4}
    return gains[nonlinearity]
