"""paddle_tpu.nn.functional — NN ops.

Reference parity: `python/paddle/nn/functional/` backed by phi kernels
(conv `phi/kernels/gpu/conv_kernel.cu`, softmax, layer_norm, pooling,
cross_entropy `phi/kernels/gpu/cross_entropy_kernel.cu`, ...). Convolutions
lower to `lax.conv_general_dilated` (MXU), pools to `lax.reduce_window`;
attention routes to the Pallas flash kernel when beneficial.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import _dispatch as _d
from ...ops._bn_common import _bn_axes, _bn_stats
from ...ops._dispatch import kernel
from ...framework import random as random_mod
from ...framework.tensor import Tensor

__all__ = []  # populated at bottom


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# ===========================================================================
# activations
# ===========================================================================
def _act(name, fn):
    @kernel(name)
    def impl(x, _fn=fn):
        return _fn(x)
    def op(x, name=None, _impl=impl, _nm=name):
        return _d.call(_impl, (x,), name=_nm)
    op.__name__ = name
    return op


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _relu_outgrad(x):
    return jnp.maximum(x, 0)


def _relu_outgrad_fwd(x):
    out = jnp.maximum(x, 0)
    # save the OUTPUT, not the input: d relu/dx = 1[out>0] exactly (same
    # x=0 subgradient as 1[x>0]). In conv->bn->relu chains the output is
    # the next layer's input residual and stays live anyway, so the relu
    # INPUT (the BN result) dies at the forward fusion boundary — XLA then
    # never materializes it, saving a write + a backward read per pair
    # (reference analog: fused_bn_activation_op.cu keeps only y + mask)
    return out, out


def _relu_outgrad_bwd(out, dy):
    return (jnp.where(out > 0, dy, jnp.zeros((), dy.dtype)),)


_relu_outgrad.defvjp(_relu_outgrad_fwd, _relu_outgrad_bwd)

relu = _act("relu", _relu_outgrad)
relu6 = _act("relu6", jax.nn.relu6)
silu = _act("silu", jax.nn.silu)
swish = _act("swish", jax.nn.silu)
mish = _act("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
sigmoid = _act("sigmoid", jax.nn.sigmoid)
log_sigmoid = _act("log_sigmoid", jax.nn.log_sigmoid)
tanh = _act("tanh", jnp.tanh)
tanhshrink = _act("tanhshrink", lambda x: x - jnp.tanh(x))
softsign = _act("softsign", jax.nn.soft_sign)
selu = _act("selu", jax.nn.selu)


def gelu(x, approximate=False, name=None):
    @kernel("gelu")
    def impl(a, *, approximate):
        return jax.nn.gelu(a, approximate=approximate)
    return _d.call(impl, (x,), dict(approximate=approximate), name="gelu")


def leaky_relu(x, negative_slope=0.01, name=None):
    @kernel("leaky_relu")
    def impl(a, *, ns):
        return jax.nn.leaky_relu(a, negative_slope=ns)
    return _d.call(impl, (x,), dict(ns=negative_slope), name="leaky_relu")


def elu(x, alpha=1.0, name=None):
    @kernel("elu")
    def impl(a, *, alpha):
        return jax.nn.elu(a, alpha=alpha)
    return _d.call(impl, (x,), dict(alpha=alpha), name="elu")


def celu(x, alpha=1.0, name=None):
    @kernel("celu")
    def impl(a, *, alpha):
        return jax.nn.celu(a, alpha=alpha)
    return _d.call(impl, (x,), dict(alpha=alpha), name="celu")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    @kernel("hardtanh")
    def impl(a, *, min, max):
        return jnp.clip(a, min, max)
    return _d.call(impl, (x,), dict(min=min, max=max), name="hardtanh")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    @kernel("hardsigmoid")
    def impl(a, *, slope, offset):
        return jnp.clip(slope * a + offset, 0.0, 1.0)
    return _d.call(impl, (x,), dict(slope=slope, offset=offset), name="hardsigmoid")


def hardswish(x, name=None):
    @kernel("hardswish")
    def impl(a):
        return a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0
    return _d.call(impl, (x,), name="hardswish")


def hardshrink(x, threshold=0.5, name=None):
    @kernel("hardshrink")
    def impl(a, *, t):
        return jnp.where(jnp.abs(a) > t, a, 0.0)
    return _d.call(impl, (x,), dict(t=threshold), name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    @kernel("softshrink")
    def impl(a, *, t):
        return jnp.where(a > t, a - t, jnp.where(a < -t, a + t, 0.0))
    return _d.call(impl, (x,), dict(t=threshold), name="softshrink")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    @kernel("softplus")
    def impl(a, *, beta, threshold):
        return jnp.where(beta * a > threshold, a, jax.nn.softplus(beta * a) / beta)
    return _d.call(impl, (x,), dict(beta=beta, threshold=threshold), name="softplus")


def thresholded_relu(x, threshold=1.0, name=None):
    @kernel("thresholded_relu")
    def impl(a, *, t):
        return jnp.where(a > t, a, 0.0)
    return _d.call(impl, (x,), dict(t=threshold), name="thresholded_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    @kernel("prelu")
    def impl(a, w, *, data_format):
        if w.size == 1:
            wb = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a > 0, a, wb * a)
    return _d.call(impl, (x, weight), dict(data_format=data_format), name="prelu")


def glu(x, axis=-1, name=None):
    @kernel("glu")
    def impl(a, *, axis):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return _d.call(impl, (x,), dict(axis=axis), name="glu")


def maxout(x, groups, axis=1, name=None):
    @kernel("maxout")
    def impl(a, *, groups, axis):
        c = a.shape[axis]
        new_shape = a.shape[:axis] + (c // groups, groups) + a.shape[axis + 1:]
        return jnp.max(a.reshape(new_shape), axis=axis + 1)
    return _d.call(impl, (x,), dict(groups=groups, axis=axis), name="maxout")


def softmax(x, axis=-1, dtype=None, name=None):
    @kernel("softmax")
    def impl(a, *, axis):
        return jax.nn.softmax(a, axis=axis)
    out = _d.call(impl, (x,), dict(axis=axis), name="softmax")
    if dtype is not None:
        out = out.astype(dtype)
    return out


def log_softmax(x, axis=-1, dtype=None, name=None):
    @kernel("log_softmax")
    def impl(a, *, axis):
        return jax.nn.log_softmax(a, axis=axis)
    out = _d.call(impl, (x,), dict(axis=axis), name="log_softmax")
    if dtype is not None:
        out = out.astype(dtype)
    return out


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = random_mod.next_key()

    @kernel("gumbel_softmax")
    def impl(a, *, temperature, hard, axis, key=key):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            y_hard = jax.nn.one_hot(idx, y.shape[axis], axis=axis, dtype=y.dtype)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return _d.call(impl, (x,), dict(temperature=temperature, hard=hard, axis=axis),
                   name="gumbel_softmax")


# ===========================================================================
# linear / embedding
# ===========================================================================
@kernel("linear")
def _linear(x, w, b=None):
    pet = jnp.float32 if x.dtype in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)) else None
    out = jnp.matmul(x, w, preferred_element_type=pet)
    if pet is not None:
        out = out.astype(x.dtype)
    if b is not None:
        out = out + b
    return out


def linear(x, weight, bias=None, name=None):
    if bias is None:
        return _d.call(lambda a, w: _linear(a, w), (x, weight), name="linear")
    return _d.call(_linear, (x, weight, bias), name="linear")


@kernel("embedding")
def _embedding(x, weight, *, padding_idx):
    idx = x.astype(jnp.int32)
    out = jnp.take(weight, idx, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    if padding_idx is not None and padding_idx < 0:
        padding_idx = weight.shape[0] + padding_idx
    return _d.call(_embedding, (x, weight), dict(padding_idx=padding_idx))


def one_hot(x, num_classes, name=None):
    @kernel("one_hot")
    def impl(a, *, n):
        return jax.nn.one_hot(a.astype(jnp.int32), n, dtype=jnp.float32)
    return _d.call(impl, (x,), dict(n=num_classes), name="one_hot", nondiff=True)


# ===========================================================================
# dropout
# ===========================================================================
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            @kernel("dropout_infer_scale")
            def impl_s(a, *, p):
                return a * (1.0 - p)
            return _d.call(impl_s, (x,), dict(p=p), name="dropout")
        from ...ops import assign
        return assign(x)
    key = random_mod.next_key()

    @kernel("dropout")
    def impl(a, *, p, axis, mode, key=key):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return _d.call(impl, (x,), dict(p=p, axis=axis, mode=mode), name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        from ...ops import assign
        return assign(x)
    key = random_mod.next_key()

    @kernel("alpha_dropout")
    def impl(a, *, p, key=key):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_c = (q + alpha_p ** 2 * q * p) ** -0.5
        b_c = -a_c * alpha_p * p
        return (a_c * jnp.where(keep, a, alpha_p) + b_c).astype(a.dtype)
    return _d.call(impl, (x,), dict(p=p), name="alpha_dropout")


# ===========================================================================
# convolution
# ===========================================================================
def _conv_nd(x, w, bias, stride, padding, dilation, groups, data_format, nd,
             name="conv"):
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    if isinstance(padding, str):
        pad = padding.upper()
    elif isinstance(padding, (list, tuple)) and len(padding) == 2 * nd:
        pad = [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nd)]
    elif isinstance(padding, (list, tuple)) and padding and isinstance(padding[0], (list, tuple)):
        # paddle full-form [[0,0],[0,0],[h0,h1],[w0,w1]]
        sp = padding[2:] if data_format.startswith("NC") else padding[1:-1]
        pad = [(int(p[0]), int(p[1])) for p in sp]
    else:
        p = _pair(padding, nd)
        pad = [(pi, pi) for pi in p]

    if data_format in ("NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + "DHW"[3 - nd:]
    else:
        lhs_spec = "N" + "DHW"[3 - nd:] + "C"
    rhs_spec = "OI" + "DHW"[3 - nd:]
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers((1,) * (nd + 2), (1,) * (nd + 2),
                                        (lhs_spec, rhs_spec, out_spec))

    @kernel(name)
    def impl(a, w, *b, stride=stride, pad=pad, dilation=dilation, groups=groups,
             dn=dn, lhs_spec=lhs_spec):
        # no preferred_element_type: the MXU accumulates bf16 convs in fp32
        # natively, and the conv transpose (gradient) rule rejects
        # mixed-dtype operands that pet's fp32 cotangents would create
        out = jax.lax.conv_general_dilated(
            a, w.astype(a.dtype), window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[lhs_spec.index("C")] = b[0].size
            out = out + b[0].reshape(bias_shape).astype(out.dtype)
        return out

    args = (x, w) if bias is None else (x, w, bias)
    return _d.call(impl, args, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    data_format, 1, name="conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    data_format, 2, name="conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    data_format, 3, name="conv3d")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCHW", output_size=None, name=None):
    nd = 2
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    p = _pair(padding, nd) if not isinstance(padding, str) else padding

    @kernel("conv2d_transpose")
    def impl(a, w, *b, stride=stride, p=p, dilation=dilation, groups=groups):
        # weight layout (in, out, kh, kw) — gradient-of-conv trick:
        # conv_transpose = conv_general_dilated with lhs_dilation=stride
        kh, kw = w.shape[2], w.shape[3]
        if isinstance(p, str):
            raise NotImplementedError("str padding for conv_transpose")
        pad = [(dilation[i] * (k - 1) - p[i], dilation[i] * (k - 1) - p[i])
               for i, k in enumerate((kh, kw))]
        w_flip = jnp.flip(w, axis=(2, 3))
        if groups > 1:
            ci = w.shape[0]
            w_g = w_flip.reshape(groups, ci // groups, *w.shape[1:])
            w_t = jnp.concatenate([jnp.swapaxes(w_g[g], 0, 1) for g in range(groups)], axis=0)
        else:
            w_t = jnp.swapaxes(w_flip, 0, 1)  # (out, in, kh, kw)
        dn = jax.lax.conv_dimension_numbers(a.shape, w_t.shape, ("NCHW", "OIHW", "NCHW"))
        out = jax.lax.conv_general_dilated(
            a, w_t, window_strides=(1, 1), padding=pad,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if b:
            out = out + b[0].reshape(1, -1, 1, 1)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    return _d.call(impl, args, name="conv2d_transpose")


# ===========================================================================
# pooling
# ===========================================================================
def _pool2d(x, kernel_size, stride, padding, mode, ceil_mode=False,
            exclusive=True, data_format="NCHW", name="pool2d"):
    ks = _pair(kernel_size)
    st = _pair(stride if stride is not None else kernel_size)
    pd = _pair(padding)
    nchw = data_format == "NCHW"
    window = (1, 1, ks[0], ks[1]) if nchw else (1, ks[0], ks[1], 1)
    strides = (1, 1, st[0], st[1]) if nchw else (1, st[0], st[1], 1)
    pads = ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])) if nchw else \
           ((0, 0), (pd[0], pd[0]), (pd[1], pd[1]), (0, 0))

    @kernel(name)
    def impl(a, *, window=window, strides=strides, pads=pads, mode=mode,
             exclusive=exclusive):
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides, pads)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, pads)
        if exclusive and any(p[0] or p[1] for p in pads):
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
            return s / cnt
        denom = np.prod([w for w in window])
        return s / denom
    return _d.call(impl, (x,), name=name)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    out = _pool2d(x, kernel_size, stride, padding, "max", ceil_mode,
                  data_format=data_format, name="max_pool2d")
    if return_mask:
        if data_format != "NCHW" or ceil_mode:
            raise NotImplementedError(
                "max_pool2d return_mask supports NCHW without ceil_mode")
        from .extra import _pool_indices
        return out, _pool_indices(x, kernel_size, stride, padding, 2)
    return out


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool2d(x, kernel_size, stride, padding, "avg", ceil_mode, exclusive,
                   data_format, name="avg_pool2d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    from ...ops import unsqueeze, squeeze
    out = max_pool2d(unsqueeze(x, -1), (kernel_size, 1),
                     (stride or kernel_size, 1), (padding, 0))
    return squeeze(out, -1)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    from ...ops import unsqueeze, squeeze
    out = avg_pool2d(unsqueeze(x, -1), (kernel_size, 1),
                     (stride or kernel_size, 1), (padding, 0), exclusive=exclusive)
    return squeeze(out, -1)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    os = _pair(output_size)

    @kernel("adaptive_avg_pool2d")
    def impl(a, *, os=os, nchw=(data_format == "NCHW")):
        h_ax, w_ax = (2, 3) if nchw else (1, 2)
        H, W = a.shape[h_ax], a.shape[w_ax]
        oh, ow = os
        if H % oh == 0 and W % ow == 0:
            if nchw:
                r = a.reshape(a.shape[0], a.shape[1], oh, H // oh, ow, W // ow)
                return r.mean(axis=(3, 5))
            r = a.reshape(a.shape[0], oh, H // oh, ow, W // ow, a.shape[3])
            return r.mean(axis=(2, 4))
        # general case: per-output-cell variable windows via segment means
        out = jax.image.resize(a, a.shape[:h_ax] + (oh, ow) + a.shape[w_ax + 1:],
                               method="linear")
        return out
    return _d.call(impl, (x,), name="adaptive_avg_pool2d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    os = _pair(output_size)

    @kernel("adaptive_max_pool2d")
    def impl(a, *, os=os):
        H, W = a.shape[2], a.shape[3]
        oh, ow = os
        assert H % oh == 0 and W % ow == 0, "adaptive_max_pool needs divisible sizes"
        r = a.reshape(a.shape[0], a.shape[1], oh, H // oh, ow, W // ow)
        return r.max(axis=(3, 5))
    return _d.call(impl, (x,), name="adaptive_max_pool2d")


def adaptive_avg_pool1d(x, output_size, name=None):
    from ...ops import unsqueeze, squeeze
    out = adaptive_avg_pool2d(unsqueeze(x, -1), (output_size, 1))
    return squeeze(out, -1)


# ===========================================================================
# normalization
# ===========================================================================
@kernel("layer_norm")
def _layer_norm(x, weight, bias, *, normalized_ndim, epsilon):
    if normalized_ndim == 1 and weight is not None and bias is not None:
        # hot path: fused kernel with custom vjp (single HBM pass fwd,
        # stats recomputed in bwd) — ops/pallas/layer_norm.py
        from ...ops.pallas.layer_norm import fused_layer_norm
        return fused_layer_norm(x, weight, bias, epsilon)
    axes = tuple(range(x.ndim - normalized_ndim, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(normalized_shape)
    if weight is None and bias is None:
        return _d.call(lambda a, *, normalized_ndim, epsilon:
                       _layer_norm(a, None, None, normalized_ndim=normalized_ndim,
                                   epsilon=epsilon),
                       (x,), dict(normalized_ndim=nd, epsilon=epsilon), name="layer_norm")
    return _d.call(_layer_norm, (x, weight, bias),
                   dict(normalized_ndim=nd, epsilon=epsilon), name="layer_norm")


@kernel("rms_norm")
def _rms_norm(x, weight, *, epsilon):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(ms + epsilon)
    return (out * weight.astype(jnp.float32)).astype(x.dtype)


def rms_norm(x, weight, epsilon=1e-6, name=None):
    return _d.call(_rms_norm, (x, weight), dict(epsilon=epsilon))


@kernel("batch_norm_infer")
def _bn_infer(x, rm, rv, w, b, *, epsilon, data_format):
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    shape = [1] * x.ndim
    shape[c_axis] = x.shape[c_axis]
    inv = jax.lax.rsqrt(rv.reshape(shape) + epsilon)
    out = (x - rm.reshape(shape)) * inv
    if w is not None:
        out = out * w.reshape(shape)
    if b is not None:
        out = out + b.reshape(shape)
    return out.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train_core(x, w, b, epsilon, data_format):
    out, _, _ = _bn_train_fwd_impl(x, w, b, epsilon, data_format)
    return out


def _bn_train_fwd_impl(x, w, b, epsilon, data_format):
    axes, shape = _bn_axes(x, data_format)
    # fp32 statistics WITHOUT materializing an fp32 copy of x: the casts
    # fuse into the reductions/normalize, so traffic stays bf16-sized
    mean, var = _bn_stats(x, axes)
    inv = jax.lax.rsqrt(var + epsilon)
    out = (x.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
    if w is not None:
        out = out * w.reshape(shape).astype(jnp.float32)
    if b is not None:
        out = out + b.reshape(shape).astype(jnp.float32)
    return out.astype(x.dtype), mean, var


def _bn_train_core_fwd(x, w, b, epsilon, data_format):
    out, mean, var = _bn_train_fwd_impl(x, w, b, epsilon, data_format)
    inv = jax.lax.rsqrt(var + epsilon)
    # residuals: x by REFERENCE (it is live in HBM anyway — the conv
    # output) + tiny per-channel stats. The pre-custom-vjp version let
    # jax.vjp save a fresh fp32 copy of every BN input, which alone was
    # ~10GB/step of ResNet-50 b128 HBM traffic.
    return out, (x, w, b is None, mean, inv)


def _bn_train_core_bwd(epsilon, data_format, res, dy):
    x, w, b_none, mean, inv = res
    axes, shape = _bn_axes(x, data_format)
    n = 1
    for a in axes:
        n *= x.shape[a]
    dyf = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
    dbeta = jnp.sum(dyf, axis=axes)
    g = dyf if w is None else dyf * w.reshape(shape).astype(jnp.float32)
    # classic fused BN backward: dx = inv*(g - mean(g) - xhat*mean(g*xhat))
    gm = jnp.sum(g, axis=axes) / n
    gxm = jnp.sum(g * xhat, axis=axes) / n
    dx = inv.reshape(shape) * (g - gm.reshape(shape)
                               - xhat * gxm.reshape(shape))
    dw = None if w is None else jnp.sum(dyf * xhat, axis=axes).astype(w.dtype)
    db = None if b_none else dbeta
    return dx.astype(x.dtype), dw, db


_bn_train_core.defvjp(_bn_train_core_fwd, _bn_train_core_bwd)


@kernel("batch_norm_train")
def _bn_train(x, w, b, *, epsilon, data_format):
    out = _bn_train_core(x, w, b, epsilon, data_format)
    axes, _ = _bn_axes(x, data_format)
    # running-stat updates reuse the same fused reductions (identical
    # subgraphs to the core's; XLA CSEs them within one program)
    mean, var = _bn_stats(x, axes)
    return out, mean, var


@kernel("fused_bn_relu")
def _fused_bn_act_train(x, w, b, *, epsilon, data_format, act):
    from ...ops.pallas.fused_bn import fused_bn_relu
    return fused_bn_relu(x, w, b, epsilon=epsilon, data_format=data_format,
                         act=act)


@kernel("fused_bn_add_relu")
def _fused_bn_add_act_train(x, z, w, b, *, epsilon, data_format, act):
    from ...ops.pallas.fused_bn import fused_bn_add_relu
    return fused_bn_add_relu(x, z, w, b, epsilon=epsilon,
                             data_format=data_format, act=act)


@kernel("batch_norm_infer_act")
def _bn_infer_act(x, rm, rv, w, b, *rest, epsilon, data_format, act):
    """Inference-mode BN with the same act/add epilogue as the fused train
    kernels, so a fused layer behaves identically in eval mode (XLA fuses
    the whole chain; no custom kernel needed off the train hot path)."""
    out = _bn_infer(x, rm, rv, w, b, epsilon=epsilon, data_format=data_format)
    if rest:
        out = out + rest[0]
    if act == "relu":
        out = jnp.maximum(out, 0)
    return out.astype(x.dtype)


def _update_running_stats(running_mean, running_var, mean, var, momentum):
    """Momentum update of the running-stat buffers (reference
    batch_norm_kernel.cu mean_out/variance_out semantics) — ONE definition
    shared by batch_norm and conv2d_bn so the fused conv path can never
    drift from the unfused one."""
    if isinstance(running_mean, Tensor):
        with jax.default_matmul_precision("float32"):
            m = momentum
            running_mean.data = (running_mean.data * m
                                 + mean.data * (1 - m)).astype(
                                     running_mean.data.dtype)
            running_var.data = (running_var.data * m
                                + var.data * (1 - m)).astype(
                                    running_var.data.dtype)


def _bn_affine_arrays(x, weight, bias, data_format):
    """The fused kernels require concrete gamma/beta arrays; a disabled
    affine (weight_attr=False) substitutes constants that take no grad."""
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    C = (x.shape[c_axis] if not isinstance(x, Tensor)
         else x.data.shape[c_axis])
    w = jnp.ones((C,), jnp.float32) if weight is None else weight
    b = jnp.zeros((C,), jnp.float32) if bias is None else bias
    return w, b


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None, act=None, residual=None):
    """Functional batch norm. In training mode also updates running stats
    in-place on the provided Tensors (reference semantics:
    `phi/kernels/gpu/batch_norm_kernel.cu` updates mean_out/variance_out).

    `act`/`residual` select the fused BN(+add)+activation kernels
    (reference `fused_bn_activation_op.cu` / `fused_bn_add_activation_op.cu`,
    Pallas on TPU): out = act(BN(x) [+ residual]). Running-stat momentum
    semantics are identical to the unfused path.
    """
    if use_global_stats is None:
        use_global_stats = not training
    if act is None and residual is None:
        if use_global_stats:
            return _d.call(_bn_infer,
                           (x, running_mean, running_var, weight, bias),
                           dict(epsilon=epsilon, data_format=data_format),
                           name="batch_norm")
        out, mean, var = _d.call(_bn_train, (x, weight, bias),
                                 dict(epsilon=epsilon, data_format=data_format),
                                 name="batch_norm")
    else:
        w, b = _bn_affine_arrays(x, weight, bias, data_format)
        attrs = dict(epsilon=epsilon, data_format=data_format, act=act)
        if use_global_stats:
            args = (x, running_mean, running_var, w, b)
            if residual is not None:
                args = args + (residual,)
            return _d.call(_bn_infer_act, args, attrs,
                           name="batch_norm_infer_act")
        if residual is not None:
            out, mean, var = _d.call(_fused_bn_add_act_train,
                                     (x, residual, w, b), attrs,
                                     name="fused_bn_add_relu")
        else:
            out, mean, var = _d.call(_fused_bn_act_train, (x, w, b), attrs,
                                     name="fused_bn_relu")
    _update_running_stats(running_mean, running_var, mean, var, momentum)
    return out


@kernel("fused_conv_bn_relu")
def _fused_conv_bn_train(x, w, g, b, *, epsilon, act):
    from ...ops.pallas.fused_conv_bn import fused_conv1x1_bn_act
    return fused_conv1x1_bn_act(x, w, g, b, epsilon=epsilon, act=act)


@kernel("fused_conv_bn_add_relu")
def _fused_conv_bn_add_train(x, z, w, g, b, *, epsilon, act):
    from ...ops.pallas.fused_conv_bn import fused_conv1x1_bn_act
    return fused_conv1x1_bn_act(x, w, g, b, residual=z, epsilon=epsilon,
                                act=act)


def conv2d_bn(x, conv_weight, running_mean, running_var, weight=None,
              bias=None, training=False, momentum=0.9, epsilon=1e-5,
              stride=1, padding=0, dilation=1, groups=1,
              data_format="NCHW", use_global_stats=None, act=None,
              residual=None, name=None):
    """Fused conv2d + training-mode batch_norm(+residual add)(+act).

    The ResNet block-tail primitive: when the conv is a 1x1/stride-1/
    channels-last shape the fused Pallas chain
    (`ops/pallas/fused_conv_bn.py`) computes the matmul and the BN batch
    statistics in ONE pass over the output — eliminating the separate
    full-activation stats read the composed path pays — then applies
    normalize(+add)+act via the fused-BN elementwise kernel. Every other
    shape (3x3/7x7, strided, grouped, NCHW, CPU) falls back to the exact
    `conv2d` -> `batch_norm(act=, residual=)` composition, so this is
    always safe to call. Running-stat momentum semantics are identical to
    `batch_norm` (shared helper).
    """
    if use_global_stats is None:
        use_global_stats = not training
    from ...ops.pallas import fused_conv_bn as _fcb
    xs = tuple(x.data.shape) if isinstance(x, Tensor) else tuple(x.shape)
    xdt = x.data.dtype if isinstance(x, Tensor) else x.dtype
    ws = tuple(conv_weight.data.shape) if isinstance(conv_weight, Tensor) \
        else tuple(conv_weight.shape)
    if (not use_global_stats) and _fcb.eligible(
            xs, ws, stride, padding, dilation, groups, data_format, xdt):
        # the BN affine is sized by the conv OUTPUT channels (w_shape[0]),
        # not x's channel axis — _bn_affine_arrays reads the latter and
        # would build a (Cin,) substitute for a disabled affine
        Cout = int(ws[0])
        w_ = jnp.ones((Cout,), jnp.float32) if weight is None else weight
        b_ = jnp.zeros((Cout,), jnp.float32) if bias is None else bias
        attrs = dict(epsilon=epsilon, act=act)
        if residual is not None:
            out, mean, var = _d.call(
                _fused_conv_bn_add_train,
                (x, residual, conv_weight, w_, b_), attrs,
                name="fused_conv_bn_add_relu")
        else:
            out, mean, var = _d.call(
                _fused_conv_bn_train, (x, conv_weight, w_, b_), attrs,
                name="fused_conv_bn_relu")
        _update_running_stats(running_mean, running_var, mean, var,
                              momentum)
        return out
    y = conv2d(x, conv_weight, None, stride, padding, dilation, groups,
               data_format)
    return batch_norm(y, running_mean, running_var, weight, bias,
                      training=training, momentum=momentum, epsilon=epsilon,
                      data_format=data_format,
                      use_global_stats=use_global_stats, act=act,
                      residual=residual)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    @kernel("instance_norm")
    def impl(a, *wb, eps=eps):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        if wb:
            shape = (1, -1) + (1,) * (a.ndim - 2)
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        return out
    args = (x,) if weight is None else ((x, weight) if bias is None else (x, weight, bias))
    return _d.call(impl, args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    @kernel("group_norm")
    def impl(a, *wb, ng=num_groups, eps=epsilon, nchw=(data_format == "NCHW")):
        if not nchw:
            a = jnp.moveaxis(a, -1, 1)
        N, C = a.shape[0], a.shape[1]
        g = a.reshape(N, ng, C // ng, *a.shape[2:])
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + eps)).reshape(a.shape)
        if wb:
            shape = (1, C) + (1,) * (a.ndim - 2)
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        if not nchw:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = (x,) if weight is None else ((x, weight) if bias is None else (x, weight, bias))
    return _d.call(impl, args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    @kernel("local_response_norm")
    def impl(a, *, size, alpha, beta, k):
        sq = jnp.square(a)
        half = size // 2
        pad = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2)
        sq_p = jnp.pad(sq, pad)
        win = sum(jax.lax.slice_in_dim(sq_p, i, i + a.shape[1], axis=1)
                  for i in range(size))
        return a / jnp.power(k + alpha * win / size, beta)
    return _d.call(impl, (x,), dict(size=size, alpha=alpha, beta=beta, k=k),
                   name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    @kernel("normalize")
    def impl(a, *, p, axis, eps):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, eps)
    return _d.call(impl, (x,), dict(p=p, axis=axis, eps=epsilon), name="normalize")


# ===========================================================================
# losses
# ===========================================================================
def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True,
                  label_smoothing=0.0, name=None):
    @kernel("cross_entropy")
    def impl(logits, lab, *w, ignore_index=ignore_index, reduction=reduction,
             soft_label=soft_label, axis=axis, use_softmax=use_softmax,
             label_smoothing=label_smoothing):
        n_cls = logits.shape[axis]
        if soft_label:
            logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
                else jnp.log(jnp.maximum(logits, 1e-30))
            soft = lab
            if label_smoothing > 0.0:
                soft = soft * (1.0 - label_smoothing) + label_smoothing / n_cls
            nll = -jnp.sum(soft * logp, axis=axis)
        else:
            li = lab.astype(jnp.int32)
            if li.ndim == logits.ndim and li.shape[axis] == 1:
                li = jnp.squeeze(li, axis)
            # hard labels: nll = logsumexp(logits) - logits[label]. No dense
            # one-hot and no materialized log-probs array — at LM vocab
            # sizes the [batch, seq, vocab] fp32 logp write dominates HBM
            # traffic (the loss is bandwidth-bound, SURVEY §7)
            safe = jnp.clip(li, 0, n_cls - 1)  # ignore_index masked below
            ax = axis if axis >= 0 else logits.ndim + axis
            from ...ops.pallas import softmax_ce as _sce
            if (use_softmax and not w and label_smoothing == 0.0
                    and ax == logits.ndim - 1 and li.shape == logits.shape[:-1]
                    and _sce.fused_softmax_ce_eligible(logits, li)):
                # LM-head hot path (SURVEY §7): fused Pallas softmax+CE —
                # bwd writes (softmax - onehot)·dnll straight in the logits
                # dtype, no fp32 [N, V] cotangent. Out-of-range labels give
                # nll = lse here; the shared mask below zeroes them and
                # their cotangent, so dlogits rows vanish too.
                nll = _sce.fused_softmax_ce(logits, li)
            elif use_softmax:
                picked = jnp.squeeze(
                    jnp.take_along_axis(logits, jnp.expand_dims(safe, ax),
                                        axis=ax), ax).astype(jnp.float32)
                lse = jax.scipy.special.logsumexp(
                    logits.astype(jnp.float32), axis=ax)
                nll = lse - picked
                if label_smoothing > 0.0:
                    # smoothed CE adds eps * mean-over-classes of -logp
                    mean_logit = jnp.mean(logits.astype(jnp.float32), axis=ax)
                    nll = (1.0 - label_smoothing) * nll \
                        + label_smoothing * (lse - mean_logit)
            else:
                picked = jnp.squeeze(
                    jnp.take_along_axis(logits, jnp.expand_dims(safe, ax),
                                        axis=ax), ax).astype(jnp.float32)
                nll = -jnp.log(jnp.maximum(picked, 1e-30))
                if label_smoothing > 0.0:
                    mean_logp = jnp.mean(
                        jnp.log(jnp.maximum(logits.astype(jnp.float32),
                                            1e-30)), axis=ax)
                    nll = (1.0 - label_smoothing) * nll \
                        - label_smoothing * mean_logp
        if w:
            if soft_label:
                ww = jnp.take(w[0], jnp.argmax(soft, axis=axis), axis=0)
            else:
                safe_li = jnp.clip(li.reshape(nll.shape), 0, n_cls - 1)
                ww = jnp.take(w[0], safe_li, axis=0)
            nll = nll * ww
        if not soft_label:
            li_f = li.reshape(nll.shape)
            # ANY out-of-range label contributes zero loss (the removed
            # one_hot formulation had this property; the gather path clips,
            # so it must mask explicitly), not just ignore_index itself
            mask = ((li_f != ignore_index) & (li_f >= 0)
                    & (li_f < n_cls))
            nll = jnp.where(mask, nll, 0.0)
            if reduction == "mean":
                denom = jnp.sum(jnp.where(mask, ww, 0.0)) if w else \
                    jnp.maximum(jnp.sum(mask), 1)
                return jnp.sum(nll) / denom
        return _reduce_loss(nll, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return _d.call(impl, args, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from ...ops import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    @kernel("nll_loss")
    def impl(logp, lab, *w, ignore_index=ignore_index, reduction=reduction):
        li = lab.astype(jnp.int32)
        n_cls = logp.shape[-1 if logp.ndim == li.ndim + 1 else 1]
        safe_li = jnp.clip(li, 0, n_cls - 1)
        nll = -jnp.take_along_axis(
            logp, safe_li[..., None] if logp.ndim == li.ndim + 1 else safe_li,
            axis=-1 if logp.ndim == li.ndim + 1 else 1)
        nll = nll.reshape(li.shape)
        ww = jnp.take(w[0], safe_li, axis=0) if w else None
        if ww is not None:
            nll = nll * ww
        mask = li != ignore_index
        nll = jnp.where(mask, nll, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(mask, ww, 0.0)) if w else \
                jnp.maximum(jnp.sum(mask), 1)
            return jnp.sum(nll) / denom
        return _reduce_loss(nll, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return _d.call(impl, args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    @kernel("mse_loss")
    def impl(a, b, *, reduction=reduction):
        return _reduce_loss(jnp.square(a - b), reduction)
    return _d.call(impl, (input, label), name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    @kernel("l1_loss")
    def impl(a, b, *, reduction=reduction):
        return _reduce_loss(jnp.abs(a - b), reduction)
    return _d.call(impl, (input, label), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    @kernel("smooth_l1_loss")
    def impl(a, b, *, reduction=reduction, delta=delta):
        d = a - b
        ad = jnp.abs(d)
        loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce_loss(loss, reduction)
    return _d.call(impl, (input, label), name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    @kernel("binary_cross_entropy")
    def impl(p, y, *w, reduction=reduction):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce_loss(loss, reduction)
    args = (input, label) if weight is None else (input, label, weight)
    return _d.call(impl, args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    @kernel("bce_with_logits")
    def impl(z, y, *extra, reduction=reduction, has_w=(weight is not None),
             has_pw=(pos_weight is not None)):
        i = 0
        w = extra[i] if has_w else None
        i += 1 if has_w else 0
        pw = extra[i] if has_pw else None
        max_val = jnp.clip(-z, 0, None)
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.log(jnp.exp(-max_val) +
                                                  jnp.exp(-z - max_val)) + max_val)
        else:
            loss = (1 - y) * z + max_val + jnp.log(jnp.exp(-max_val) +
                                                   jnp.exp(-z - max_val))
        if w is not None:
            loss = loss * w
        return _reduce_loss(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return _d.call(impl, tuple(args), name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    @kernel("kl_div")
    def impl(logp, y, *, reduction=reduction):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce_loss(loss, reduction)
    return _d.call(impl, (input, label), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    @kernel("margin_ranking_loss")
    def impl(a, b, y, *, margin=margin, reduction=reduction):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce_loss(loss, reduction)
    return _d.call(impl, (input, other, label), name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    @kernel("hinge_embedding_loss")
    def impl(a, y, *, margin=margin, reduction=reduction):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce_loss(loss, reduction)
    return _d.call(impl, (input, label), name="hinge_embedding_loss")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    @kernel("cosine_similarity")
    def impl(a, b, *, axis=axis, eps=eps):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return _d.call(impl, (x1, x2), name="cosine_similarity")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    @kernel("sigmoid_focal_loss")
    def impl(z, y, *n, alpha=alpha, gamma=gamma, reduction=reduction):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce_loss(loss, reduction)
    args = (logit, label) if normalizer is None else (logit, label, normalizer)
    return _d.call(impl, args, name="sigmoid_focal_loss")


def square_error_cost(input, label):
    @kernel("square_error_cost")
    def impl(a, b):
        return jnp.square(a - b)
    return _d.call(impl, (input, label), name="square_error_cost")


# ===========================================================================
# vision / misc
# ===========================================================================
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    nchw = data_format == "NCHW"
    if isinstance(x, Tensor):
        shp = x.shape
    else:
        shp = list(jnp.asarray(x).shape)
    H, W = (shp[2], shp[3]) if nchw else (shp[1], shp[2])
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
            (scale_factor, scale_factor)
        size = (int(H * sf[0]), int(W * sf[1]))
    size = tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in size)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic",
              "linear": "linear", "trilinear": "linear", "area": "linear"}[mode]

    @kernel("interpolate")
    def impl(a, *, size=size, method=method, nchw=nchw):
        if nchw:
            out_shape = a.shape[:2] + size
        else:
            out_shape = (a.shape[0],) + size + (a.shape[3],)
        return jax.image.resize(a, out_shape, method=method)
    return _d.call(impl, (x,), name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    @kernel("pixel_shuffle")
    def impl(a, *, r=upscale_factor):
        N, C, H, W = a.shape
        out = a.reshape(N, C // (r * r), r, r, H, W)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(N, C // (r * r), H * r, W * r)
    return _d.call(impl, (x,), name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    @kernel("pixel_unshuffle")
    def impl(a, *, r=downscale_factor):
        N, C, H, W = a.shape
        out = a.reshape(N, C, H // r, r, W // r, r)
        out = out.transpose(0, 1, 3, 5, 2, 4)
        return out.reshape(N, C * r * r, H // r, W // r)
    return _d.call(impl, (x,), name="pixel_unshuffle")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = _pair(paddings)
    dl = _pair(dilations)

    @kernel("unfold")
    def impl(a, *, ks=ks, st=st, pd=pd, dl=dl):
        N, C, H, W = a.shape
        a_p = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        oh = (H + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        ow = (W + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        cols = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                patch = jax.lax.slice(
                    a_p, (0, 0, i * dl[0], j * dl[1]),
                    (N, C, i * dl[0] + (oh - 1) * st[0] + 1,
                     j * dl[1] + (ow - 1) * st[1] + 1),
                    (1, 1, st[0], st[1]))
                cols.append(patch.reshape(N, C, -1))
        out = jnp.stack(cols, axis=2)  # N, C, kh*kw, L
        return out.reshape(N, C * ks[0] * ks[1], -1)
    return _d.call(impl, (x,), name="unfold")


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    arr = lengths.data if isinstance(lengths, Tensor) else jnp.asarray(lengths)
    if maxlen is None:
        maxlen = int(np.asarray(arr).max())

    @kernel("sequence_mask")
    def impl(l, *, maxlen=maxlen):
        return (jnp.arange(maxlen) < l[..., None]).astype(jnp.int32)
    return _d.call(impl, (lengths,), name="sequence_mask", nondiff=True)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    @kernel("label_smooth")
    def impl(y, *, eps=epsilon):
        n = y.shape[-1]
        return y * (1 - eps) + eps / n
    return _d.call(impl, (label,), name="label_smooth")


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    @kernel("diag_embed")
    def impl(a, *, offset=offset):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), dtype=a.dtype)
        rows = jnp.arange(a.shape[-1]) + max(-offset, 0)
        cols = jnp.arange(a.shape[-1]) + max(offset, 0)
        return out.at[..., rows, cols].set(a)
    return _d.call(impl, (input,), name="diag_embed")


# ---------------------------------------------------------------------------
# attention (used by nn.MultiHeadAttention and transformer models)
# ---------------------------------------------------------------------------
def _sp_ring_config(query, key, attn_mask, dropout_p=0.0):
    """(mesh, axis, mode) when sequence parallelism should route to ring or
    Ulysses attention: an active HCG with sp>1, no arbitrary mask,
    self-attention (q/k chunked identically), seq divisible by the axis.
    mode follows `hcg.sp_mode` ("ring" default; "ulysses" when configured
    AND heads divide the axis AND attention dropout is off — the ring
    regenerates per-chunk weight-dropout masks in O(L), while Ulysses'
    local full-sequence attention would fall back to materialized [L, L]
    probabilities under dropout)."""
    if attn_mask is not None:
        return None
    if key.shape[1] != query.shape[1]:
        return None  # cross-attention: ring chunking assumes Lq == Lk
    try:
        from ...distributed.topology import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
    except Exception:
        return None
    if hcg is None:
        return None
    sizes = dict(zip(hcg.mesh.axis_names, hcg.mesh.devices.shape))
    sp = sizes.get("sp", 1)
    if sp <= 1:
        return None
    L = query.shape[1]
    if L % sp != 0:
        return None
    mode = getattr(hcg, "sp_mode", "ring")
    if mode == "ulysses" and (query.shape[2] % sp != 0 or dropout_p > 0.0):
        mode = "ring"  # heads not divisible / weight dropout: fall back
    return hcg.mesh, "sp", mode


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """Batched attention; [B, L, H, D] layout (paddle convention).

    Routing: ring attention over the `sp` mesh axis when sequence/context
    parallelism is active (long-context path — no chip materializes full
    K/V), else the Pallas flash kernel on TPU for long sequences, else the
    XLA composition.

    `dropout_p` drops attention WEIGHTS (the post-softmax probabilities),
    matching the reference (`nn/layer/transformer.py:412-415` drops
    `weights` before the @V matmul) — NOT the attention output. Round-2
    review (VERDICT weak #3) found the output-features variant here;
    weight dropout with `dropout_p > 0` routes dense attention to the XLA
    path (see `flash_attention` docstring); under sequence parallelism it
    routes to the RING (even when `sp_mode="ulysses"`), whose per-chunk
    masks are regenerated in the backward pass in O(L) memory.
    """
    p_eff = dropout_p if training else 0.0
    drop_key = random_mod.next_key() if p_eff > 0.0 else None
    sp_ring = _sp_ring_config(query, key, attn_mask, p_eff)
    if sp_ring is not None:
        mesh, axis, mode = sp_ring
        if mode == "ulysses":
            from ...ops.pallas.ulysses import ulysses_attention as sp_attn
        else:
            from ...ops.pallas.ring_attention import ring_attention as sp_attn

        @kernel("sp_attention")
        def ring_impl(q, k, v, is_causal=is_causal, _mesh=mesh, _axis=axis,
                      _fn=sp_attn, _p=p_eff, _key=drop_key):
            return _fn(q, k, v, mesh=_mesh, axis_name=_axis,
                       causal=is_causal, dropout_p=_p, dropout_key=_key)
        return _d.call(ring_impl, (query, key, value), name="sp_attention")

    @kernel("sdpa")
    def impl(q, k, v, *m, is_causal=is_causal, _p=p_eff, _key=drop_key):
        from ...ops.pallas.flash_attention import flash_attention
        mask = m[0] if m else None
        return flash_attention(q, k, v, mask=mask, causal=is_causal,
                               dropout_p=_p, dropout_key=_key)
    args = (query, key, value) if attn_mask is None else (query, key, value, attn_mask)
    return _d.call(impl, args, name="sdpa")


def _collect_exports():
    import types
    g = globals()
    return [k for k, v in g.items()
            if not k.startswith("_") and isinstance(v, types.FunctionType)]


__all__ = _collect_exports()


# completion sweep (pooling3d/pad/unpool/ctc/grid_sample/...)
from .extra import *  # noqa: F401,F403,E402
