"""Functional ops completing the `paddle.nn.functional` surface.

Reference files cited per function; implementations are jnp/lax
compositions (XLA fuses them) dispatched through the eager tape like every
other op (`ops/_dispatch.call`).
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...ops import _dispatch as _d
from ...ops._dispatch import kernel
from ...framework.tensor import Tensor


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# ------------------------------- padding ------------------------------------

_PAD_MODES = {"constant": "constant", "reflect": "reflect",
              "replicate": "edge", "circular": "wrap"}


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """reference functional/common.py pad: `pad` is per-spatial-dim
    [left, right, (top, bottom, (front, back))] — last dims first — or a
    full per-dim list of len 2*ndim."""
    nd = x.ndim if hasattr(x, "ndim") else len(x.shape)
    pad = [int(p) for p in pad]

    if len(pad) == 2 * nd:  # full form: [d0_lo, d0_hi, d1_lo, d1_hi, ...]
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        n_spatial = len(pad) // 2
        widths = [(0, 0)] * nd
        # spatial dims: last n_spatial dims for NC-first formats; pad list
        # orders innermost dim first (W, then H, then D)
        channel_last = data_format.endswith("C")
        for i in range(n_spatial):
            dim = (nd - 1 - i) - (1 if channel_last else 0)
            widths[dim] = (pad[2 * i], pad[2 * i + 1])

    @kernel("pad_nd")
    def impl(a, *, widths=tuple(widths), mode=mode, value=value):
        m = _PAD_MODES[mode]
        if m == "constant":
            return jnp.pad(a, widths, mode=m, constant_values=value)
        return jnp.pad(a, widths, mode=m)
    return _d.call(impl, (x,), name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, _pair(padding, 4), mode="constant", value=0.0,
               data_format=data_format)


# ------------------------------- pooling ------------------------------------

def _pool_nd(x, kernel_size, stride, padding, nd, op, ceil_mode,
             exclusive=True, name="pool"):
    ks = _pair(kernel_size, nd)
    st = _pair(stride if stride is not None else kernel_size, nd)
    pd = _pair(padding, nd)
    # ceil_mode: extend the high-side padding so the output size ceils
    # (the extra positions only see pad values, which the avg path excludes
    # from its divisor via the count window)
    hi_extra = [0] * nd
    if ceil_mode:
        for i in range(nd):
            size = int(x.shape[2 + i])
            out_ceil = -(-(size + 2 * pd[i] - ks[i]) // st[i]) + 1
            # paddle/torch clamp: the last window must START within the
            # input + left padding, else it would cover only padding
            while out_ceil > 1 and (out_ceil - 1) * st[i] >= size + pd[i]:
                out_ceil -= 1
            need = (out_ceil - 1) * st[i] + ks[i] - (size + 2 * pd[i])
            hi_extra[i] = max(0, need)
    hi_extra = tuple(hi_extra)

    @kernel(name)
    def impl(a, *, ks=ks, st=st, pd=pd, op=op, exclusive=exclusive,
             hi=hi_extra):
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0)) + tuple((p, p + h) for p, h in zip(pd, hi))
        if op == "max":
            init = -jnp.inf
            out = jax.lax.reduce_window(a, init, jax.lax.max, window,
                                        strides, pads)
            return out
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add,
                                  window, strides, pads)
        if exclusive and (any(pd) or any(hi)):
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, pads)
            return s / cnt
        return s / float(np.prod(ks))
    return _d.call(impl, (x,), name=name)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    out = _pool_nd(x, kernel_size, stride, padding, 3, "max", ceil_mode,
                   name="max_pool3d")
    if return_mask:
        if data_format != "NCDHW" or ceil_mode:
            raise NotImplementedError(
                "max_pool3d return_mask supports NCDHW without ceil_mode")
        idx = _pool_indices(x, kernel_size, stride, padding, 3)
        return out, idx
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool_nd(x, kernel_size, stride, padding, 3, "avg", ceil_mode,
                    exclusive=exclusive, name="avg_pool3d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    os = _pair(output_size, 3)

    @kernel("adaptive_avg_pool3d")
    def impl(a, *, os=os):
        B, C, D, H, W = a.shape
        a = a.reshape(B, C, os[0], D // os[0], os[1], H // os[1],
                      os[2], W // os[2])
        return a.mean(axis=(3, 5, 7))
    return _d.call(impl, (x,), name="adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    os = int(output_size) if not isinstance(output_size, (list, tuple)) \
        else int(output_size[0])

    @kernel("adaptive_max_pool1d")
    def impl(a, *, os=os):
        B, C, L = a.shape
        return a.reshape(B, C, os, L // os).max(axis=3)
    out = _d.call(impl, (x,), name="adaptive_max_pool1d")
    if return_mask:
        raise NotImplementedError("adaptive_max_pool1d return_mask")
    return out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    os = _pair(output_size, 3)

    @kernel("adaptive_max_pool3d")
    def impl(a, *, os=os):
        B, C, D, H, W = a.shape
        a = a.reshape(B, C, os[0], D // os[0], os[1], H // os[1],
                      os[2], W // os[2])
        return a.max(axis=(3, 5, 7))
    out = _d.call(impl, (x,), name="adaptive_max_pool3d")
    if return_mask:
        raise NotImplementedError("adaptive_max_pool3d return_mask")
    return out


def _pool_indices(x, kernel_size, stride, padding, nd):
    """Argmax indices (flat per-channel) for max_unpool, like the
    reference's max_pool return_mask."""
    ks = _pair(kernel_size, nd)
    st = _pair(stride if stride is not None else kernel_size, nd)
    pd = _pair(padding, nd)

    @kernel("max_pool_indices")
    def impl(a, *, ks=ks, st=st, pd=pd):
        spatial = a.shape[2:]
        flat_idx = jnp.arange(int(np.prod(spatial))).reshape(spatial)
        flat_idx = jnp.broadcast_to(flat_idx, a.shape).astype(jnp.float32)
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pd)

        def sel(acc, cur):
            acc_v, acc_i = acc
            cur_v, cur_i = cur
            take = cur_v > acc_v
            return (jnp.where(take, cur_v, acc_v),
                    jnp.where(take, cur_i, acc_i))
        (vals, idx) = jax.lax.reduce_window(
            (a, flat_idx), (-jnp.inf, -1.0), sel, window, strides, pads)
        return idx.astype(jnp.int32)
    return _d.call(impl, (x,), name="max_pool_indices", nondiff=True)


def _max_unpool_nd(x, indices, kernel_size, stride, padding, nd,
                   output_size=None, name="max_unpool"):
    ks = _pair(kernel_size, nd)
    st = _pair(stride if stride is not None else kernel_size, nd)
    pdd = _pair(padding, nd)
    if output_size is None:
        # inverse of the pool output formula, INCLUDING padding — the flat
        # indices reference the unpadded input layout
        out_spatial = tuple(
            (int(x.shape[2 + i]) - 1) * st[i] + ks[i] - 2 * pdd[i]
            for i in range(nd))
    else:
        out_spatial = tuple(int(s) for s in output_size[-nd:])

    @kernel(name)
    def impl(a, idx, *, out_spatial=out_spatial):
        B, C = a.shape[:2]
        n_out = int(np.prod(out_spatial))
        flat_v = a.reshape(B, C, -1)
        flat_i = idx.reshape(B, C, -1).astype(jnp.int32)
        out = jnp.zeros((B, C, n_out), a.dtype)
        out = jax.vmap(jax.vmap(
            lambda o, i, v: o.at[i].set(v)))(out, flat_i, flat_v)
        return out.reshape((B, C) + out_spatial)
    return _d.call(impl, (x, indices), name=name)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool_nd(x, indices, kernel_size, stride, padding, 1,
                          output_size, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool_nd(x, indices, kernel_size, stride, padding, 2,
                          output_size, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool_nd(x, indices, kernel_size, stride, padding, 3,
                          output_size, "max_unpool3d")


# -------------------------- conv transposes ---------------------------------

def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       groups, dilation, nd, name, output_size=None):
    st = _pair(stride, nd)
    dil = _pair(dilation, nd)
    pd = _pair(padding, nd)
    opd = list(_pair(output_padding, nd))
    if output_size is not None:
        want = _pair(output_size, nd)
        for i in range(nd):
            k = int(weight.shape[2 + i])
            default = (int(x.shape[2 + i]) - 1) * st[i] \
                + dil[i] * (k - 1) + 1 - 2 * pd[i]
            extra = want[i] - default
            if not (0 <= extra < st[i] or (extra == 0 and st[i] == 1)):
                raise ValueError(
                    f"conv_transpose output_size[{i}]={want[i]} unreachable "
                    f"(default {default}, stride {st[i]})")
            opd[i] = extra
    opd = tuple(opd)

    @kernel(name)
    def impl(a, w, *b, st=st, pd=pd, dil=dil, groups=groups, opd=opd):
        k = w.shape[2:]
        # gradient-of-conv: conv with lhs_dilation=stride
        pads = tuple((dil[i] * (k[i] - 1) - pd[i],
                      dil[i] * (k[i] - 1) - pd[i] + opd[i])
                     for i in range(nd))
        # weight (in, out/g, *k) -> flip spatial, PER-GROUP io swap (a
        # global swap would mix groups; see conv2d_transpose)
        wt = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        if groups > 1:
            ci = w.shape[0]
            w_g = wt.reshape((groups, ci // groups) + w.shape[1:])
            wt = jnp.concatenate(
                [jnp.swapaxes(w_g[g], 0, 1) for g in range(groups)], axis=0)
        else:
            wt = jnp.swapaxes(wt, 0, 1)  # (out, in, *k)
        dn = jax.lax.conv_dimension_numbers(
            a.shape, wt.shape,
            ("NC" + "DHW"[-nd:], "OI" + "DHW"[-nd:], "NC" + "DHW"[-nd:]))
        out = jax.lax.conv_general_dilated(
            a, wt, window_strides=(1,) * nd, padding=pads,
            lhs_dilation=st, rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups)
        if b:
            out = out + b[0].reshape((1, -1) + (1,) * nd)
        return out
    args = (x, weight) if bias is None else (x, weight, bias)
    return _d.call(impl, args, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, groups, dilation, 1,
                              "conv1d_transpose", output_size=output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, groups, dilation, 3,
                              "conv3d_transpose", output_size=output_size)


# ----------------------------- fold / unfold --------------------------------

def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (reference functional/common.py fold): x [B, C*kh*kw, L]."""
    oh, ow = _pair(output_sizes, 2)
    kh, kw = _pair(kernel_sizes, 2)
    sh, sw = _pair(strides, 2)
    ph, pw = _pair(paddings, 2)
    dh, dw = _pair(dilations, 2)

    @kernel("fold")
    def impl(a, *, oh=oh, ow=ow, kh=kh, kw=kw, sh=sh, sw=sw, ph=ph, pw=pw,
             dh=dh, dw=dw):
        B, CKK, L = a.shape
        C = CKK // (kh * kw)
        n_h = (oh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        n_w = (ow + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        a = a.reshape(B, C, kh, kw, n_h, n_w)
        out = jnp.zeros((B, C, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[:, :, hi:hi + n_h * sh:sh,
                             wj:wj + n_w * sw:sw].add(a[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]
    return _d.call(impl, (x,), name="fold")


# ------------------------- spatial transforms -------------------------------

def affine_grid(theta, out_shape, align_corners=True, name=None):
    """reference vision.py affine_grid: theta [B,2,3] -> grid [B,H,W,2]."""
    if not isinstance(out_shape, (list, tuple)):
        out_shape = [int(s) for s in np.asarray(out_shape)]
    B, C, H, W = [int(s) for s in out_shape]

    @kernel("affine_grid")
    def impl(th, *, H=H, W=W, align=align_corners):
        if align:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) + 0.5) * 2.0 / W - 1.0
            ys = (jnp.arange(H) + 0.5) * 2.0 / H - 1.0
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [HW,3]
        grid = jnp.einsum("bij,nj->bni", th, base)                # [B,HW,2]
        return grid.reshape(th.shape[0], H, W, 2)
    return _d.call(impl, (theta,), name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """reference vision.py grid_sample: x [B,C,H,W], grid [B,Hg,Wg,2]."""

    @kernel("grid_sample")
    def impl(a, g, *, mode=mode, pad=padding_mode, align=align_corners):
        B, C, H, W = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align:
            fx = (gx + 1.0) * (W - 1) / 2.0
            fy = (gy + 1.0) * (H - 1) / 2.0
        else:
            fx = ((gx + 1.0) * W - 1.0) / 2.0
            fy = ((gy + 1.0) * H - 1.0) / 2.0

        def gather(ix, iy):
            inside = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
            ixc = jnp.clip(ix, 0, W - 1)
            iyc = jnp.clip(iy, 0, H - 1)
            vals = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(
                a, iyc, ixc)  # [B, C, Hg, Wg]? -> img[:,yy,xx]: [C,Hg,Wg]
            if pad == "zeros":
                vals = vals * inside[:, None].astype(vals.dtype)
            return vals

        if mode == "nearest":
            return gather(jnp.round(fx).astype(jnp.int32),
                          jnp.round(fy).astype(jnp.int32))
        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = (fx - x0)[:, None]
        wy = (fy - y0)[:, None]
        v00 = gather(x0, y0)
        v01 = gather(x1, y0)
        v10 = gather(x0, y1)
        v11 = gather(x1, y1)
        top = v00 * (1 - wx) + v01 * wx
        bot = v10 * (1 - wx) + v11 * wx
        return top * (1 - wy) + bot * wy
    return _d.call(impl, (x, grid), name="grid_sample")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """reference extension.py temporal_shift (TSM video op)."""

    @kernel("temporal_shift")
    def impl(a, *, seg_num=seg_num, ratio=shift_ratio):
        NT, C, H, W = a.shape
        B = NT // seg_num
        a = a.reshape(B, seg_num, C, H, W)
        fold_c = int(C * ratio)
        left = jnp.concatenate(
            [a[:, 1:, :fold_c], jnp.zeros_like(a[:, :1, :fold_c])], axis=1)
        right = jnp.concatenate(
            [jnp.zeros_like(a[:, :1, fold_c:2 * fold_c]),
             a[:, :-1, fold_c:2 * fold_c]], axis=1)
        rest = a[:, :, 2 * fold_c:]
        return jnp.concatenate([left, right, rest],
                               axis=2).reshape(NT, C, H, W)
    return _d.call(impl, (x,), name="temporal_shift")


# ------------------------------- losses -------------------------------------

def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC (reference loss.py ctc_loss over warpctc): log-semiring forward
    over the extended label sequence, scan over time.

    log_probs: [T, B, V] (time-major, reference convention); labels [B, S].
    """

    @kernel("ctc_loss")
    def impl(logp, lab, in_len, lab_len, *, blank=blank,
             reduction=reduction, norm_by_times=norm_by_times):
        if logp.ndim == 3 and logp.shape[0] != lab.shape[0]:
            pass  # already [T,B,V]
        T, B, V = logp.shape
        S = lab.shape[1]
        logp = jax.nn.log_softmax(logp.astype(jnp.float32), axis=-1)
        # extended labels with interleaved blanks: length 2S+1
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        ext_len = 2 * lab_len.astype(jnp.int32) + 1
        NEG = -1e30

        # can-skip mask: ext[s] != blank and ext[s] != ext[s-2]
        skip_ok = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], axis=1)

        def emit(t_logp, s_ids):
            return jnp.take_along_axis(t_logp, s_ids, axis=1)  # [B, 2S+1]

        alpha0 = jnp.full((B, 2 * S + 1), NEG)
        alpha0 = alpha0.at[:, 0].set(emit(logp[0], ext[:, :1])[:, 0])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(ext_len > 1, emit(logp[0], ext[:, 1:2])[:, 0], NEG))

        def step(alpha, t_logp):
            shift1 = jnp.concatenate(
                [jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
            shift2 = jnp.concatenate(
                [jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
            shift2 = jnp.where(skip_ok, shift2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
            return merged + emit(t_logp, ext), None

        def masked_step(carry, inp):
            alpha, t = carry
            t_logp = inp
            new_alpha, _ = step(alpha, t_logp)
            # freeze rows whose sequence already ended (t >= in_len)
            active = (t < in_len)[:, None]
            alpha = jnp.where(active, new_alpha, alpha)
            return (alpha, t + 1), None

        (alpha, _), _ = jax.lax.scan(masked_step, (alpha0, jnp.ones((), jnp.int32)),
                                     logp[1:])
        idx_last = ext_len - 1
        a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(idx_last - 1, 0)[:, None], axis=1)[:, 0]
        # zero-length labels: ext is the single blank path, there is no
        # "previous" state — idx_last-1 would alias state 0 and double-count
        a_prev = jnp.where(ext_len > 1, a_prev, NEG)
        nll = -jnp.logaddexp(a_last, a_prev)
        if norm_by_times:
            # warpctc norm_by_times: per-sample loss scaled by 1/T_i
            nll = nll / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            return jnp.mean(nll / jnp.maximum(lab_len.astype(jnp.float32), 1))
        if reduction == "sum":
            return jnp.sum(nll)
        return nll
    return _d.call(impl, (log_probs, labels, input_lengths, label_lengths),
                   name="ctc_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    @kernel("dice_loss")
    def impl(p, y, *, eps=epsilon):
        y1 = jax.nn.one_hot(y.squeeze(-1).astype(jnp.int32), p.shape[-1],
                            dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y1, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y1, axis=reduce_dims)
        return jnp.mean(1.0 - (2 * inter + eps) / (union + eps))
    return _d.call(impl, (input, label), name="dice_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    @kernel("log_loss")
    def impl(p, y, *, eps=epsilon):
        return -y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps)
    return _d.call(impl, (input, label), name="log_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    @kernel("npair_loss")
    def impl(a, p, y, *, l2=l2_reg):
        sim = a @ p.T  # [B,B]
        same = (y[:, None] == y[None, :]).astype(sim.dtype)
        same = same / jnp.sum(same, axis=1, keepdims=True)
        xent = jnp.mean(jnp.sum(
            -same * jax.nn.log_softmax(sim, axis=1), axis=1))
        reg = l2 * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1)))
        return xent + reg
    return _d.call(impl, (anchor, positive, labels), name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid with the default complete binary tree
    (reference loss.py hsigmoid_loss)."""
    code_len = int(math.ceil(math.log2(max(num_classes, 2))))

    @kernel("hsigmoid_loss")
    def impl(x, y, w, *b, num_classes=num_classes, code_len=code_len):
        y = y.reshape(-1).astype(jnp.int32)
        # complete binary tree, 1-indexed heap: leaf(label) = label + n,
        # internal nodes 1..n-1 carry the classifiers. Path lengths VARY per
        # label — mask out steps once a path has passed the root.
        node = y + num_classes
        nll = jnp.zeros(y.shape, x.dtype)
        for _ in range(code_len + 1):
            bit = (node % 2).astype(x.dtype)
            parent = node // 2
            valid = (parent >= 1) & (parent <= num_classes - 1)
            widx = jnp.clip(parent - 1, 0, w.shape[0] - 1)
            logit = jnp.sum(x * w[widx], axis=1)
            if b:
                logit = logit + b[0][widx]
            term = jax.nn.softplus(logit) - bit * logit
            nll = nll + jnp.where(valid, term, 0.0)
            node = parent
        return jnp.mean(nll)
    args = (input, label, weight) if bias is None else (input, label, weight, bias)
    return _d.call(impl, args, name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-style margin softmax (reference loss.py margin_cross_entropy)."""

    @kernel("margin_cross_entropy")
    def impl(lg, y, *, m1=margin1, m2=margin2, m3=margin3, s=scale,
             reduction=reduction):
        y = y.reshape(-1).astype(jnp.int32)
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos)
        target = jnp.cos(m1 * theta + m2) - m3
        onehot = jax.nn.one_hot(y, lg.shape[-1], dtype=lg.dtype)
        adj = jnp.where(onehot > 0, target, cos) * s
        logp = jax.nn.log_softmax(adj, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        if reduction == "mean":
            return jnp.mean(nll)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll
    loss = _d.call(impl, (logits, label), name="margin_cross_entropy")
    if return_softmax:
        from . import softmax as _softmax
        return loss, _softmax(logits)
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """reference common.py class_center_sample (PartialFC): keep positive
    class centers + uniform negatives; remap labels."""
    lab = np.asarray(label.numpy() if isinstance(label, Tensor) else label
                     ).reshape(-1)
    pos = np.unique(lab)
    if pos.size >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        rng = np.random.default_rng()  # fresh entropy: negatives must vary
        extra = rng.choice(rest, size=num_samples - pos.size, replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(sampled.size)
    return (Tensor(jnp.asarray(remap[lab])),
            Tensor(jnp.asarray(sampled.astype(np.int64))))


# ------------------------------ misc ----------------------------------------

def bilinear(x1, x2, weight, bias=None, name=None):
    """reference common.py bilinear: out[b,o] = x1[b,i] W[o,i,j] x2[b,j]."""

    @kernel("bilinear")
    def impl(a, b_, w, *bias_):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b_)
        if bias_:
            out = out + bias_[0]
        return out
    args = (x1, x2, weight) if bias is None else (x1, x2, weight, bias)
    return _d.call(impl, args, name="bilinear")


def gather_tree(ids, parents):
    """reference rnn.py gather_tree (beam search backtrace):
    ids/parents [T, B, beam]."""

    @kernel("gather_tree")
    def impl(ids, par):
        T = ids.shape[0]

        def step(nxt, t_inp):
            t_ids, t_par = t_inp
            cur = jnp.take_along_axis(t_ids, nxt, axis=-1)
            prev = jnp.take_along_axis(t_par, nxt, axis=-1)
            return prev, cur
        beams = jnp.broadcast_to(
            jnp.arange(ids.shape[2]), ids.shape[1:]).astype(jnp.int32)
        _, out_rev = jax.lax.scan(step, beams, (ids.astype(jnp.int32),
                                                par.astype(jnp.int32)),
                                  reverse=True)
        return out_rev
    return _d.call(impl, (ids, parents), name="gather_tree", nondiff=True)


def sparse_attention(query, key, value, sparse_csr_offset=None,
                     sparse_csr_columns=None, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Parity entry (reference sparse_attention.py, CUDA-only): on TPU the
    flash-attention kernel covers the memory-bound long-seq case; the block-
    sparse pattern is ignored (dense attention is computed)."""
    from . import scaled_dot_product_attention
    return scaled_dot_product_attention(query, key, value,
                                        attn_mask=attn_mask)


# in-place activations (rebind, reference *_ ops)
def relu_(x, name=None):
    from . import relu
    x.data = relu(x).data
    return x


def elu_(x, alpha=1.0, name=None):
    from . import elu
    x.data = elu(x, alpha).data
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    from . import softmax
    x.data = softmax(x, axis=axis).data
    return x


def tanh_(x, name=None):
    from ...ops.math import tanh
    x.data = tanh(x).data
    return x


__all__ = [
    "pad", "zeropad2d", "max_pool3d", "avg_pool3d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool3d", "max_unpool1d",
    "max_unpool2d", "max_unpool3d", "conv1d_transpose", "conv3d_transpose",
    "fold", "affine_grid", "grid_sample", "temporal_shift", "ctc_loss",
    "dice_loss", "log_loss", "npair_loss", "hsigmoid_loss",
    "margin_cross_entropy", "class_center_sample", "bilinear", "gather_tree",
    "sparse_attention", "relu_", "elu_", "softmax_", "tanh_",
]
