"""Program rewrite-pass framework.

Reference: the IR pass system (`/root/reference/paddle/fluid/framework/ir/`
— `Pass`/`PassRegistry`, ~100 passes, 61.5k LoC). On TPU the fusion and
memory passes are XLA's job, but repo-side graph rewrites still need a
structured home (round-1 review: "amp/quant/fusion-hint rewrites have no
home"). A Pass here rewrites the recorded-op `static.Program`
(`static/__init__.py` `_OpNode` list) in place and bumps `program.version`
so compiled-executable caches invalidate.

Built-in passes:
  * `amp_cast_pass`        — static AMP (reference `contrib/mixed_precision/
                             fp16_utils.py` cast insertion): white-listed
                             matmul-class ops compute in bf16/fp16, outputs
                             cast back to fp32.
  * `quant_insertion_pass` — QAT-style fake-quant around white-listed ops
                             (reference `slim/quantization/quantization_pass
                             .py` InsertQuantPass).
  * `constant_folding_pass`— classic constant folding: ops whose inputs are
                             all constants are evaluated once at pass time
                             and their results embedded (reference
                             `constant_folding_pass.cc`).
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

__all__ = ["Pass", "PassRegistry", "register_pass", "apply_pass",
           "AmpCastPass", "QuantInsertionPass", "ConstantFoldingPass"]

# ops that benefit from reduced precision / quantization (MXU-bound);
# mirrors the reference's white list shape (fp16_lists.py)
_MATMUL_CLASS = ("matmul", "linear", "conv2d", "mm", "bmm", "addmm",
                 "conv1d", "conv3d", "einsum")


class Pass:
    """Base rewrite pass (reference ir::Pass)."""

    name = "pass"

    def apply(self, program) -> None:
        raise NotImplementedError

    def __call__(self, program):
        self.apply(program)
        program.version += 1
        return program


class PassRegistry:
    _passes: Dict[str, Callable[[], Pass]] = {}

    @classmethod
    def register(cls, name: str, factory: Callable[[], Pass]):
        cls._passes[name] = factory

    @classmethod
    def get(cls, name: str, **kwargs) -> Pass:
        if name not in cls._passes:
            raise KeyError(f"unknown pass {name!r}; registered: "
                           f"{sorted(cls._passes)}")
        return cls._passes[name](**kwargs)

    @classmethod
    def list(cls) -> List[str]:
        return sorted(cls._passes)


def register_pass(name: str):
    def deco(klass):
        klass.name = name
        PassRegistry.register(name, klass)
        return klass
    return deco


def apply_pass(program, name_or_pass, **kwargs):
    """Apply one pass (by registry name or instance) to a Program."""
    p = (name_or_pass if isinstance(name_or_pass, Pass)
         else PassRegistry.get(name_or_pass, **kwargs))
    return p(program)


def _is_float(aval) -> bool:
    return hasattr(aval, "dtype") and jnp.issubdtype(aval.dtype, jnp.floating)


@register_pass("amp_cast_pass")
class AmpCastPass(Pass):
    """White-listed ops compute in `dtype`, their outputs cast back to the
    recorded aval dtype — so downstream ops (and fetch shapes) are
    unchanged, exactly the reference's cast-insertion contract."""

    def __init__(self, dtype=jnp.bfloat16, white_list=None):
        self.dtype = jnp.dtype(dtype)
        self.white_list = tuple(white_list or _MATMUL_CLASS)

    def _matches(self, name: str) -> bool:
        return any(name.startswith(w) for w in self.white_list)

    def apply(self, program):
        dtype = self.dtype
        for node in program.ops:
            if not self._matches(node.name):
                continue
            out_avals = [program.vars[v] for v in node.out_ids]
            node.impl = _amp_wrap(node.impl, dtype,
                                  tuple(getattr(a, "dtype", None)
                                        for a in out_avals))


def _amp_wrap(impl, dtype, out_dtypes):
    @functools.wraps(impl)
    def wrapped(*arrs, **kw):
        cast = tuple(a.astype(dtype)
                     if hasattr(a, "dtype")
                     and jnp.issubdtype(a.dtype, jnp.floating) else a
                     for a in arrs)
        out = impl(*cast, **kw)
        multi = isinstance(out, tuple)
        outs = out if multi else (out,)
        outs = tuple(o.astype(d) if d is not None
                     and jnp.issubdtype(d, jnp.floating) else o
                     for o, d in zip(outs, out_dtypes))
        return outs if multi else outs[0]
    return wrapped


@register_pass("quant_insertion_pass")
class QuantInsertionPass(Pass):
    """Fake-quantize the float inputs of white-listed ops (abs-max, STE is
    irrelevant on the inference/static path)."""

    def __init__(self, bits: int = 8, white_list=None):
        self.bits = bits
        self.white_list = tuple(white_list or _MATMUL_CLASS)

    def apply(self, program):
        bits = self.bits
        for node in program.ops:
            if not any(node.name.startswith(w) for w in self.white_list):
                continue
            node.impl = _quant_wrap(node.impl, bits)


def _quant_wrap(impl, bits):
    qmax = float(2 ** (bits - 1) - 1)

    @functools.wraps(impl)
    def wrapped(*arrs, **kw):
        qarrs = []
        for a in arrs:
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
                scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8) / qmax
                a = jnp.round(a / scale).clip(-qmax, qmax) * scale
            qarrs.append(a)
        return impl(*qarrs, **kw)
    return wrapped


@register_pass("constant_folding_pass")
class ConstantFoldingPass(Pass):
    """Evaluate ops whose inputs are all constants ONCE at pass time and
    embed the results; downstream references become constants too. Ops with
    randomness are left alone."""

    _SKIP = ("dropout", "rand", "uniform", "normal", "bernoulli", "seed")

    def apply(self, program):
        const_vals: Dict[int, object] = {}
        kept = []
        for node in program.ops:
            # rewrite inputs already known constant
            node.inputs = [("const", const_vals[ref[1]])
                           if ref[0] == "var" and ref[1] in const_vals
                           else ref for ref in node.inputs]
            foldable = (all(ref[0] == "const" for ref in node.inputs)
                        and not any(s in node.name for s in self._SKIP)
                        and not any(vid in program.grad_vids
                                    for vid in node.out_ids)
                        and all(vid != program.loss_vid
                                for vid in node.out_ids))
            if not foldable:
                kept.append(node)
                continue
            args = [ref[1] for ref in node.inputs]
            out = node.impl(*args, **node.kwargs)
            outs = out if isinstance(out, tuple) else (out,)
            for vid, val in zip(node.out_ids, outs):
                const_vals[vid] = val
        program.ops = kept
        # fetchable folded vars must stay resolvable: record their values
        if const_vals:
            folded = getattr(program, "folded_consts", {})
            folded.update(const_vals)
            program.folded_consts = folded
