"""paddle_tpu.static — static-graph mode (Program / Executor).

TPU-native redesign of the reference's static graph stack:
  * `Program` (reference `ProgramDesc`, `framework/framework.proto:236`;
    python `fluid/framework.py:4722`) — here a recorded op-graph over
    symbolic `Variable`s. Shape/dtype inference (the reference's infermeta,
    `paddle/phi/infermeta/`) is `jax.eval_shape` — XLA abstract evaluation.
  * `Executor` (reference `fluid/executor.py:613` + the C++
    StandaloneExecutor/InterpreterCore, `new_executor/interpretercore.h`) —
    here the whole Program (forward, backward, optimizer update) is replayed
    into ONE jitted pure function: XLA's scheduler plays the role of the
    InterpreterCore dependency-graph async executor, and buffer donation
    plays the role of its garbage collector.
  * `append_backward` (reference `fluid/backward.py`) — grad vars come from
    `jax.grad` over the replayed forward instead of per-op grad-op chaining.
  * `save/load_inference_model` (reference `fluid/io.py:1246,1466`) — the
    serialized artifact is a StableHLO export (`jax.export`) + params.

Op capture: every eager op routes through `ops._dispatch.call`; in static
mode a builder hook records the op into the current Program instead of
executing it (the reference's `Block.append_op` path when
`in_dygraph_mode()` is false).
"""
from __future__ import annotations

import contextlib
import functools
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.param import Parameter
from ..framework.tensor import Tensor
from ..ops import _dispatch


# ---------------------------------------------------------------------------
# Symbolic Variable
# ---------------------------------------------------------------------------
from . import passes as passes  # noqa: E402  (registered at import)
from .passes import apply_pass, PassRegistry  # noqa: E402


class Variable(Tensor):
    """Symbolic tensor in a Program (reference `fluid/framework.py:1171`).

    Carries only an abstract value (shape/dtype); `.data` yields the aval so
    shape/dtype accessors keep working, while any attempt to read concrete
    values raises.
    """

    def __init__(self, aval, prog: "Program", vid: int, name: Optional[str] = None):
        # deliberately no super().__init__: no concrete array exists
        self._aval = aval
        self._prog = prog
        self._vid = vid
        self.stop_gradient = True
        self.grad = None
        self._node = None
        self.name = name or f"var_{vid}"
        self.persistable = False

    # Tensor API reads .data for shape/dtype — serve the aval.
    @property
    def data(self):
        return self._aval

    @data.setter
    def data(self, v):
        raise RuntimeError("cannot assign data to a static Variable")

    @property
    def shape(self):
        return list(self._aval.shape)

    @property
    def dtype(self):
        return jnp.dtype(self._aval.dtype)

    @property
    def ndim(self):
        return len(self._aval.shape)

    def numpy(self):
        raise RuntimeError(
            "Variable has no value in static mode; fetch it via Executor.run")

    __array__ = numpy

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype})")

    def backward(self, *a, **kw):
        raise RuntimeError("use append_backward/optimizer.minimize in static mode")


class _OpNode:
    """One recorded op (reference OpDesc, `framework/framework.proto:50`)."""
    __slots__ = ("impl", "kwargs", "inputs", "out_ids", "name")

    def __init__(self, impl, kwargs, inputs, out_ids, name):
        self.impl = impl          # pure array fn
        self.kwargs = kwargs      # static attrs
        self.inputs = inputs      # list of ("var", vid) | ("const", array)
        self.out_ids = out_ids    # list of vids
        self.name = name


class Program:
    """Recorded static graph (reference ProgramDesc / `framework.py:4722`)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self.id = Program._counter
        self.ops: List[_OpNode] = []
        self.vars: Dict[int, Any] = {}           # vid -> aval
        self.var_names: Dict[str, int] = {}      # name -> vid (feedables/fetchables)
        self.inputs: Dict[str, int] = {}         # feed name -> vid
        self.params: Dict[str, np.ndarray] = {}  # param name -> init value
        self.param_vids: Dict[str, int] = {}     # param name -> vid
        self._param_objs: Dict[int, str] = {}    # id(Parameter) -> name
        # strong refs: without these a dead Parameter's id() can be reused by
        # a new one and alias it to the wrong program var
        self._param_refs: Dict[str, Any] = {}
        self.dyn_dims: Dict[str, tuple] = {}     # feed name -> dynamic dim idxs
        self.loss_vid: Optional[int] = None
        self.grad_vids: Dict[int, str] = {}      # grad vid -> param name
        self.optimizer = None
        self.version = 0                         # bumped per mutation for jit cache
        self._next_vid = 0
        self.random_seed = 0

    # -- construction --------------------------------------------------------
    def _new_var(self, aval, name: Optional[str] = None) -> Variable:
        vid = self._next_vid
        self._next_vid += 1
        v = Variable(aval, self, vid, name)
        self.vars[vid] = aval
        if v.name:
            self.var_names[v.name] = vid
        self.version += 1
        return v

    def _intern_input(self, t):
        """Map an op input to a recorded reference."""
        if isinstance(t, Variable):
            return ("var", t._vid)
        if isinstance(t, Parameter):
            name = self._param_objs.get(id(t))
            if name is None:
                name = t.name or f"param_{len(self.params)}"
                while name in self.params:
                    name = name + "_"
                self._param_objs[id(t)] = name
                self._param_refs[name] = t
                self.params[name] = np.asarray(t.data)
                pv = self._new_var(
                    jax.ShapeDtypeStruct(t.data.shape, t.data.dtype), name)
                self.param_vids[name] = pv._vid
            return ("var", self.param_vids[name])
        if isinstance(t, Tensor):
            return ("const", t.data)
        if isinstance(t, jax.Array):
            return ("const", t)
        if t is None:
            return ("const", None)
        a = np.asarray(t)
        if a.dtype == np.float64:
            a = a.astype(dtype_mod.get_default_dtype())
        return ("const", jnp.asarray(a))

    def append_op(self, impl, tensors, kwargs, name):
        inputs = [self._intern_input(t) for t in tensors]
        avals_in = [self.vars[ref[1]] if ref[0] == "var" else ref[1]
                    for ref in inputs]
        out_aval = jax.eval_shape(functools.partial(impl, **kwargs), *avals_in)
        multi = isinstance(out_aval, tuple)
        out_avals = out_aval if multi else (out_aval,)
        outs = tuple(self._new_var(a) for a in out_avals)
        self.ops.append(_OpNode(impl, kwargs, inputs, [o._vid for o in outs], name))
        return outs if multi else outs[0]

    # -- introspection (parity helpers) --------------------------------------
    def all_parameters(self):
        return [ParamVarView(self, n) for n in self.params]

    def list_vars(self):
        return [Variable(self.vars[vid], self, vid, n)
                for n, vid in self.var_names.items()]

    def global_block(self):
        return _BlockView(self)

    def clone(self, for_test: bool = False):
        p = Program.__new__(Program)
        p.__dict__.update(self.__dict__)
        Program._counter += 1
        p.id = Program._counter  # fresh id: Executor cache keys on (id, version)
        p.ops = list(self.ops)
        p.vars = dict(self.vars)
        p.var_names = dict(self.var_names)
        p.inputs = dict(self.inputs)
        p.params = dict(self.params)
        p.param_vids = dict(self.param_vids)
        p._param_objs = dict(self._param_objs)
        p._param_refs = dict(self._param_refs)
        p.grad_vids = dict(self.grad_vids)
        p.dyn_dims = dict(self.dyn_dims)
        if for_test:
            p.optimizer = None
        return p

    def __repr__(self):
        return (f"Program(id={self.id}, ops={len(self.ops)}, "
                f"params={list(self.params)})")

    # -- replay: Program -> pure function ------------------------------------
    def _prune_ops(self, target_vids):
        """Backward slice: ops needed to produce target_vids (reference
        `framework/prune.cc`)."""
        needed = set(target_vids)
        keep = []
        for node in reversed(self.ops):
            if any(o in needed for o in node.out_ids):
                keep.append(node)
                for r in node.inputs:
                    if r[0] == "var":
                        needed.add(r[1])
        return list(reversed(keep)), needed

    def build_forward(self, prune_to=None):
        """Return fn(feed_dict_by_name, params_by_name) -> env {vid: array}.

        With `prune_to` (a list of target vids), only the backward slice of
        ops producing them is replayed — unfed feed slots outside the slice
        are then legal (inference export drops the label input).
        """
        ops = self.ops if prune_to is None else self._prune_ops(prune_to)[0]

        def forward(feeds: Dict[str, Any], params: Dict[str, Any]):
            env: Dict[int, Any] = {}
            # values pre-computed by constant_folding_pass (passes.py)
            env.update(getattr(self, "folded_consts", {}))
            for name, vid in self.inputs.items():
                if name in feeds:
                    env[vid] = feeds[name]
            for name, vid in self.param_vids.items():
                env[vid] = params[name]
            for node in ops:
                args = []
                for r in node.inputs:
                    if r[0] == "var":
                        if r[1] not in env:
                            fname = next((n for n, v in self.inputs.items()
                                          if v == r[1]), None)
                            raise KeyError(
                                f"program input '{fname}' is required by op "
                                f"'{node.name}' but was not fed" if fname else
                                f"internal var {r[1]} undefined before op "
                                f"'{node.name}'")
                        args.append(env[r[1]])
                    else:
                        args.append(r[1])
                out = node.impl(*args, **node.kwargs)
                outs = out if isinstance(out, tuple) else (out,)
                for vid, o in zip(node.out_ids, outs):
                    env[vid] = o
            return env
        return forward


class ParamVarView:
    """Parameter handle inside a Program (persistable var)."""

    def __init__(self, prog, name):
        self._prog = prog
        self.name = name
        self.persistable = True

    @property
    def shape(self):
        return list(self._prog.params[self.name].shape)

    @property
    def dtype(self):
        return self._prog.params[self.name].dtype


class _BlockView:
    def __init__(self, prog):
        self.program = prog

    @property
    def ops(self):
        return self.program.ops

    def var(self, name):
        vid = self.program.var_names[name]
        return Variable(self.program.vars[vid], self.program, vid, name)


# ---------------------------------------------------------------------------
# default programs / program_guard / static-mode switch
# ---------------------------------------------------------------------------
_main_program = Program()
_startup_program = Program()
_static_mode = False


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


class _Builder:
    """The dispatch hook: routes op calls into the active main program."""

    def __call__(self, impl, tensors, kwargs, name):
        return _main_program.append_op(impl, tensors, kwargs, name)


_builder = _Builder()


def _enable_static():
    global _static_mode
    _static_mode = True
    _dispatch.GRAPH_BUILDER = _builder


def _disable_static():
    global _static_mode
    _static_mode = False
    _dispatch.GRAPH_BUILDER = None


def in_static_mode() -> bool:
    return _static_mode


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    global _main_program, _startup_program
    prev_m, prev_s = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
        startup_program._main = main_program
    try:
        yield
    finally:
        _main_program = prev_m
        _startup_program = prev_s


# ---------------------------------------------------------------------------
# graph inputs
# ---------------------------------------------------------------------------
def data(name: str, shape: Sequence[Optional[int]], dtype=None,
         lod_level: int = 0) -> Variable:
    """Declare a feed slot (reference `paddle.static.data`).

    `None`/-1 leading dims become a default batch dim of 1 for abstract
    evaluation; Executor re-jits per concrete feed shape (XLA wants static
    shapes — this is the padding/bucketing policy boundary).
    """
    dtype = dtype_mod.convert_dtype(dtype) if dtype is not None \
        else dtype_mod.get_default_dtype()
    shp = tuple(1 if (s is None or (isinstance(s, int) and s < 0)) else int(s)
                for s in shape)
    prog = _main_program
    v = prog._new_var(jax.ShapeDtypeStruct(shp, dtype), name)
    prog.inputs[name] = v._vid
    prog.dyn_dims[name] = tuple(
        i for i, s in enumerate(shape)
        if s is None or (isinstance(s, int) and s < 0))
    return v


class InputSpec:
    """Shape/dtype spec (reference `paddle/static/input.py` InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(t.shape, t.dtype, name or t.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Mark loss + create grad vars (reference `fluid/backward.py`).

    Grad values are produced by `jax.grad` of the replayed forward at run
    time; here we only allocate the symbolic grad vars so they can be
    fetched, mirroring `append_backward`'s (param, grad) return.
    """
    prog = loss._prog
    prog.loss_vid = loss._vid
    pairs = []
    names = (parameter_list if parameter_list is not None
             else list(prog.params.keys()))
    names = [n.name if isinstance(n, ParamVarView) else n for n in names]
    for name in names:
        aval = prog.vars[prog.param_vids[name]]
        g = prog._new_var(jax.ShapeDtypeStruct(aval.shape, aval.dtype),
                          name + "@GRAD")
        prog.grad_vids[g._vid] = name
        pairs.append((ParamVarView(prog, name), g))
    prog.version += 1
    return pairs


# ---------------------------------------------------------------------------
# Scope
# ---------------------------------------------------------------------------
class Scope:
    """Name -> value store for persistables (reference `framework/scope.h`)."""

    def __init__(self):
        self.vars: Dict[str, Any] = {}

    def find_var(self, name):
        if name not in self.vars:
            return None
        val = self.vars[name]

        class _Var:
            def get_tensor(self_inner):
                return np.asarray(val)
        return _Var()

    def var(self, name):
        self.vars.setdefault(name, None)
        return self.find_var(name)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = prev


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------
class Executor:
    """Compile-and-run a Program (reference `fluid/executor.py:613`).

    One XLA executable per (program version, feed signature, fetch set,
    train-mode) — the TPU answer to InterpreterCore's first-run
    instruction-list build + cached re-run.
    """

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, Callable] = {}

    # -- startup -------------------------------------------------------------
    def _run_startup(self, prog: Program, scope: Scope):
        main = getattr(prog, "_main", None) or prog
        for name, init in main.params.items():
            scope.vars[name] = jnp.asarray(init)
        opt = main.optimizer
        if opt is not None:
            scope.vars.pop(f"__opt_state_{main.id}__", None)
            scope.vars.pop(f"__opt_t_{main.id}__", None)

    def _ensure_params(self, prog: Program, scope: Scope):
        for name, init in prog.params.items():
            if scope.vars.get(name) is None:
                scope.vars[name] = jnp.asarray(init)

    def run(self, program: Optional[Program] = None, feed: Optional[dict] = None,
            fetch_list: Optional[list] = None, scope: Optional[Scope] = None,
            return_numpy: bool = True):
        prog = program if program is not None else _main_program
        scope = scope or _global_scope
        feed = feed or {}

        if isinstance(prog, _ExportedProgram):
            return prog.run(feed, fetch_list, return_numpy)

        # startup program: no ops, not the feed target -> initialize
        if not prog.ops and (getattr(prog, "_main", None) is not None
                             or not fetch_list):
            self._run_startup(prog, scope)
            return []

        self._ensure_params(prog, scope)
        fetch_list = fetch_list or []
        fetch_ids = []
        for f in fetch_list:
            if isinstance(f, Variable):
                fetch_ids.append(f._vid)
            elif isinstance(f, str):
                fetch_ids.append(prog.var_names[f])
            else:
                raise TypeError(f"bad fetch entry: {f!r}")

        unknown = [k for k in feed if k not in prog.inputs]
        if unknown:
            raise ValueError(
                f"feed names {unknown} not found in program inputs "
                f"{sorted(prog.inputs)}")
        feed_arrs = {k: (v.data if isinstance(v, Tensor) else jnp.asarray(v))
                     for k, v in feed.items()}
        sig = tuple(sorted((k, tuple(a.shape), str(a.dtype))
                           for k, a in feed_arrs.items()))
        train = prog.optimizer is not None
        need_grads = train or any(vid in prog.grad_vids for vid in fetch_ids)
        key = (prog.id, prog.version, sig, tuple(fetch_ids), train, need_grads)

        fn = self._cache.get(key)
        if fn is None:
            fn = self._compile(prog, fetch_ids, train, need_grads)
            self._cache[key] = fn

        params = {n: scope.vars[n] for n in prog.params}
        opt_key = f"__opt_state_{prog.id}__"
        t_key = f"__opt_t_{prog.id}__"
        if train:
            opt = prog.optimizer
            if scope.vars.get(opt_key) is None:
                scope.vars[opt_key] = opt.init_state_tree(params)
                scope.vars[t_key] = 0
            scope.vars[t_key] += 1
            t = scope.vars[t_key]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            fetches, new_params, new_opt = fn(feed_arrs, params,
                                              scope.vars[opt_key], lr, t)
            scope.vars[opt_key] = new_opt
            for n, v in new_params.items():
                scope.vars[n] = v
            if hasattr(opt, "_learning_rate") and hasattr(
                    opt._learning_rate, "step") and callable(
                    getattr(opt._learning_rate, "step", None)):
                pass  # schedulers advance via user .step() as in dygraph
        else:
            fetches = fn(feed_arrs, params)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # -- compile -------------------------------------------------------------
    def _compile(self, prog: Program, fetch_ids, train: bool, need_grads: bool):
        targets = [v for v in fetch_ids if v not in prog.grad_vids]
        if (train or need_grads) and prog.loss_vid is not None:
            targets.append(prog.loss_vid)
        forward = prog.build_forward(prune_to=targets)
        grad_names = list(prog.params.keys())

        def run_forward(feeds, params):
            env = forward(feeds, params)
            if need_grads:
                def loss_of(p):
                    e = forward(feeds, p)
                    return e[prog.loss_vid]
                grads = jax.grad(loss_of)(params)
                for gvid, pname in prog.grad_vids.items():
                    env[gvid] = grads[pname]
            else:
                grads = None
            return env, grads

        if not train:
            @jax.jit
            def fn(feeds, params):
                env, _ = run_forward(feeds, params)
                return [env[v] for v in fetch_ids]
            return fn

        opt = prog.optimizer

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def fn(feeds, params, opt_state, lr, t):
            env, grads = run_forward(feeds, params)
            if grads is None:
                def loss_of(p):
                    e = forward(feeds, p)
                    return e[prog.loss_vid]
                grads = jax.grad(loss_of)(params)
            new_params, new_opt = opt.apply_fn(params, grads, opt_state,
                                               lr=lr, t=t)
            return [env[v] for v in fetch_ids], new_params, new_opt
        return fn

    def close(self):
        self._cache.clear()


# ---------------------------------------------------------------------------
# CompiledProgram (parity shim — jit IS the compilation)
# ---------------------------------------------------------------------------
class BuildStrategy:
    def __init__(self):
        self.memory_optimize = True
        self.enable_inplace = True
        self.fuse_all_optimizer_ops = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program

    def with_data_parallel(self, *a, **kw):
        return self


# ---------------------------------------------------------------------------
# inference model save/load (reference fluid/io.py:1246,1466)
# ---------------------------------------------------------------------------
def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program: Optional[Program] = None, **kw):
    prog = program or _main_program
    scope = _global_scope
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    feed_names = [v.name for v in feed_vars]
    fetch_ids = [v._vid for v in fetch_vars]
    forward = prog.build_forward(prune_to=fetch_ids)
    params = {n: (scope.vars[n] if scope.vars.get(n) is not None
                  else jnp.asarray(init))
              for n, init in prog.params.items()}

    def infer_fn(params, *feed_arrays):
        feeds = dict(zip(feed_names, feed_arrays))
        env = forward(feeds, params)
        return tuple(env[v] for v in fetch_ids)

    from jax import export as jexport

    def _specs(symbolic: bool):
        # dynamic dims (declared None/-1 in static.data) export shape-
        # polymorphically; dim 0 shares one "batch" symbol across feeds
        sym_names: List[str] = []
        for n in feed_names:
            for i in prog.dyn_dims.get(n, ()):
                s = "batch" if i == 0 else f"d_{n}_{i}"
                if symbolic and s not in sym_names:
                    sym_names.append(s)
        syms = dict(zip(sym_names, jexport.symbolic_shape(
            ", ".join(sym_names)))) if (symbolic and sym_names) else {}
        out = []
        for n in feed_names:
            aval = prog.vars[prog.inputs[n]]
            dims = list(aval.shape)
            for i in prog.dyn_dims.get(n, ()):
                key = "batch" if i == 0 else f"d_{n}_{i}"
                if key in syms:
                    dims[i] = syms[key]
            out.append(jax.ShapeDtypeStruct(tuple(dims), aval.dtype))
        return out

    param_specs = {n: jax.ShapeDtypeStruct(p.shape, p.dtype)
                   for n, p in params.items()}
    try:
        exp = jexport.export(jax.jit(infer_fn))(param_specs, *_specs(True))
    except Exception:
        # not all graphs are shape-polymorphic; fall back to the static shapes
        exp = jexport.export(jax.jit(infer_fn))(param_specs, *_specs(False))
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exp.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({n: np.asarray(p) for n, p in params.items()}, f)
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump({"feed_names": feed_names,
                     "fetch_count": len(fetch_ids)}, f)


class _ExportedProgram:
    """Loaded inference artifact; Executor.run dispatches to it."""

    def __init__(self, exported, params, feed_names):
        self.exported = exported
        self.params = params
        self.feed_names = feed_names

    def run(self, feed, fetch_list, return_numpy=True):
        args = [jnp.asarray(feed[n]) for n in self.feed_names]
        outs = self.exported.call(self.params, *args)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def load_inference_model(path_prefix: str, executor, **kw):
    from jax import export as jexport
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    with open(path_prefix + ".pdiparams", "rb") as f:
        params = {n: jnp.asarray(p) for n, p in pickle.load(f).items()}
    with open(path_prefix + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    prog = _ExportedProgram(exported, params, meta["feed_names"])
    fetch_names = list(range(meta["fetch_count"]))
    return [prog, meta["feed_names"], fetch_names]


def normalize_program(program, feed_vars, fetch_vars):
    return program


# re-exports for `paddle.static.*` parity
from . import nn  # noqa: E402,F401

__all__ = [
    "Program", "Variable", "Executor", "Scope", "CompiledProgram",
    "BuildStrategy", "ExecutionStrategy", "InputSpec", "append_backward",
    "data", "default_main_program", "default_startup_program",
    "global_scope", "scope_guard", "program_guard", "save_inference_model",
    "load_inference_model", "normalize_program", "nn", "sparsity",
]


# paddle.static.sparsity parity (reference exposes ASP here)
from ..incubate import asp as sparsity  # noqa: E402,F401


# ---------------------------------------------------------------------------
# completion sweep: remaining paddle.static exports (reference
# python/paddle/static/__init__.py __all__)
# ---------------------------------------------------------------------------
import pickle as _pickle

import numpy as _np
import jax.numpy as _jnp


def cpu_places(device_count=None):
    import jax
    n = device_count or len([d for d in jax.devices("cpu")]) or 1
    from ..framework.place import CPUPlace
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (TPU chips here)."""
    import jax
    from ..framework.place import TPUPlace
    try:
        devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    except Exception:
        devs = []
    ids = device_ids if device_ids is not None else range(len(devs))
    return [TPUPlace(i) for i in ids]


xpu_places = cuda_places
npu_places = cuda_places
mlu_places = cuda_places


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    key = name or f"global_var_{len(_global_scope.vars)}"
    arr = _jnp.full(tuple(int(s) for s in shape), value, dtype)
    _global_scope.vars[key] = arr  # scope keys are ALWAYS strings
    from ..framework.tensor import Tensor
    t = Tensor(arr, name=key)
    t.persistable = persistable
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..ops.extras import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static autodiff entry (reference static/gradients): wraps
    append_backward's machinery for explicit target/input pairs."""
    from ..framework import tape as _tape
    ts = targets if isinstance(targets, (list, tuple)) else [targets]
    xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return _tape.grad(list(ts), list(xs), grad_outputs=target_gradients,
                      allow_unused=True)


def name_scope(prefix=None):
    """Graph-visualization name scope (no-op context, reference
    framework.name_scope)."""
    import contextlib
    return contextlib.nullcontext()


def device_guard(device=None):
    import contextlib
    return contextlib.nullcontext()


def ipu_shard_guard(index=-1, stage=-1):
    import contextlib
    return contextlib.nullcontext()


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print op (reference static/nn/common.py Print)."""
    if hasattr(input, "data") and not hasattr(input, "_prog"):
        print(message or "", _np.asarray(input.data))
    else:
        print(message or "", input)
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op (reference static/nn/common.py py_func): under our
    eager-capture static mode this is a direct call."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    return func(*xs)


# -- program/persistable serialization (reference static/io.py) -------------

def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    prog = program or default_main_program()
    return _pickle.dumps({"n_ops": len(getattr(prog, "ops", [])),
                          "params": {k: _np.asarray(v) for k, v in
                                     getattr(prog, "params", {}).items()}})


def deserialize_program(data):
    return _pickle.loads(data)


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    scope = _global_scope
    return _pickle.dumps({k: _np.asarray(v) for k, v in scope.vars.items()
                          if v is not None})


def deserialize_persistables(program, data, executor=None):
    state = _pickle.loads(data)
    for k, v in state.items():
        _global_scope.vars[k] = _jnp.asarray(v)
    return state


def save_to_file(path, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def save(program, model_prefix, protocol=4, **configs):
    """reference static/io.py save: params + program structure."""
    save_to_file(model_prefix + ".pdparams",
                 serialize_persistables(None, None, program))
    save_to_file(model_prefix + ".pdmodel.meta",
                 serialize_program(None, None, program))


def load(program, model_prefix, executor=None, var_list=None):
    deserialize_persistables(
        program, load_from_file(model_prefix + ".pdparams"), executor)


def load_program_state(model_prefix, var_list=None):
    return {k: _np.asarray(v) for k, v in _pickle.loads(
        load_from_file(model_prefix + ".pdparams")).items()}


def set_program_state(program, state_dict):
    for k, v in state_dict.items():
        _global_scope.vars[k] = _jnp.asarray(v)


def accuracy(input, label, k=1, correct=None, total=None):
    """Static accuracy op (reference static/nn/metric.py)."""
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    from ..metric import Auc
    m = Auc(num_thresholds=num_thresholds)
    m.update(input, label)
    from ..framework.tensor import Tensor
    return Tensor(_jnp.asarray(m.accumulate(), _jnp.float32))


class WeightNormParamAttr:
    """Parity config object (reference WeightNormParamAttr)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer


class ExponentialMovingAverage:
    """EMA of parameters for eval (reference static ExponentialMovingAverage);
    works over the global scope's current parameter arrays."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = {}
        self._backup = {}

    def update(self):
        for k, v in _global_scope.vars.items():
            if v is None:
                continue
            prev = self._ema.get(k)
            self._ema[k] = (_jnp.asarray(v) if prev is None
                            else self._decay * prev + (1 - self._decay)
                            * _jnp.asarray(v))

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._backup = dict(_global_scope.vars)
            for k, v in self._ema.items():
                _global_scope.vars[k] = v
            try:
                yield
            finally:
                if need_restore:
                    self.restore()
        return ctx()

    def restore(self, executor=None):
        if self._backup:
            _global_scope.vars.update(self._backup)
            self._backup = {}


class IpuStrategy:  # no IPU on this target; config shell for portability
    def __init__(self):
        self.num_ipus = 0

    def set_graph_config(self, *a, **k):
        pass


class IpuCompiledProgram:
    def __init__(self, program=None, ipu_strategy=None, scope=None):
        self.program = program

    def compile(self, *a, **k):
        return self.program


ParallelExecutor = CompiledProgram  # legacy alias: XLA partitions instead
