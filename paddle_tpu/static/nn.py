"""paddle_tpu.static.nn — static-graph layer builders.

Reference: `paddle.static.nn` (`python/paddle/static/nn/common.py` — fc,
embedding, conv2d, batch_norm, ...). Each builder creates concrete
`Parameter`s (the startup-program initializer role) and emits ops into the
current Program through the normal functional API; parameters are interned
as persistable program vars on first use.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.param import Parameter
from ..nn import functional as F
from ..nn import initializer as I

_uid = [0]


def _pname(base: str) -> str:
    _uid[0] += 1
    return f"{base}_{_uid[0]}"


def _make_param(shape, dtype, attr, default_init, base):
    init = default_init
    name = None
    if isinstance(attr, I.ParamAttr):
        name = attr.name
        if attr.initializer is not None:
            init = attr.initializer
    elif isinstance(attr, I.Initializer):
        init = attr
    dtype = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.get_default_dtype()
    data = init(tuple(shape), dtype)
    p = Parameter(data, name=name or _pname(base))
    return p


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
       bias_attr=None, activation: Optional[str] = None, name=None):
    """Fully-connected layer (reference `static/nn/common.py` fc)."""
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    from .. import ops
    if len(x.shape) > num_flatten_dims + 1:
        x = ops.reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim])
    w = _make_param([in_dim, size], x.dtype, weight_attr,
                    I.XavierUniform(), "fc_w")
    b = None
    if bias_attr is not False:
        b = _make_param([size], x.dtype, bias_attr, I.Constant(0.0), "fc_b")
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    w = _make_param(list(size), dtype, param_attr,
                    I.Normal(std=0.02), "emb_w")
    return F.embedding(input, w, padding_idx=padding_idx, sparse=is_sparse)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    w = _make_param([num_filters, in_ch // groups] + list(ks), input.dtype,
                    param_attr, I.KaimingUniform(), "conv_w")
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], input.dtype, bias_attr,
                        I.Constant(0.0), "conv_b")
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = _make_param([ch], input.dtype, param_attr, I.Constant(1.0), "bn_scale")
    offset = _make_param([ch], input.dtype, bias_attr, I.Constant(0.0), "bn_offset")
    mean = Parameter(I.Constant(0.0)((ch,), input.dtype), name=_pname("bn_mean"))
    var = Parameter(I.Constant(1.0)((ch,), input.dtype), name=_pname("bn_var"))
    mean.stop_gradient = True
    var.stop_gradient = True
    out = F.batch_norm(input, mean, var, weight=scale, bias=offset,
                       training=not is_test, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out
