"""paddle_tpu.metric — model metrics.

Reference: `python/paddle/metric/metrics.py` (Metric/Accuracy/Precision/
Recall/Auc).
"""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor


def _np(x):
    return np.asarray(x.data if isinstance(x, Tensor) else x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name="acc"):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        topk_idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = topk_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct):
        correct_np = _np(correct)
        n = correct_np.shape[0]
        accs = []
        for i, k in enumerate(self.topk):
            c = correct_np[..., :k].sum()
            self.total[i] += float(c)
            self.count[i] += n
            accs.append(float(c) / n)
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                       self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over descending thresholds
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))


def accuracy(input, label, k=1):
    pred = _np(input)
    lab = _np(label)
    if lab.ndim == pred.ndim and lab.shape[-1] == 1:
        lab = lab[..., 0]
    topk_idx = np.argsort(-pred, axis=-1)[..., :k]
    correct = (topk_idx == lab[..., None]).any(axis=-1)
    return Tensor(np.asarray(correct.mean(), np.float32))
