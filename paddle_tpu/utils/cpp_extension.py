"""Custom C++ op loading (reference `python/paddle/utils/cpp_extension/` +
`paddle/fluid/framework/custom_operator.cc`).

The reference JIT-builds a user's C++/CUDA op into a shared library and
registers it as a framework operator. TPU translation: the user's C++ runs
HOST-side (XLA owns the device), so a custom op is a compiled C function
invoked through `jax.pure_callback` — usable under jit, differentiable if
the author also provides a backward function. The C ABI is flat buffers:

    extern "C" void my_op(const float* x, float* y, long long n);

`load(name, sources)` compiles with g++ (same toolchain policy as
`paddle_tpu._native`) and returns a module-like handle; `custom_op(...)`
wraps a symbol into a Tensor-in/Tensor-out op with eager-tape autograd.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import tape as tape_mod
from ..framework.tensor import Tensor

_F32P = ctypes.POINTER(ctypes.c_float)


class CppExtension:
    """Build spec (reference setup-style CppExtension)."""

    def __init__(self, sources: Sequence[str], extra_compile_args=None,
                 include_dirs=None):
        self.sources = list(sources)
        self.extra_compile_args = list(extra_compile_args or [])
        self.include_dirs = list(include_dirs or [])


CUDAExtension = CppExtension  # no CUDA on this target; alias for portability


class _LoadedExtension:
    def __init__(self, name: str, lib: ctypes.CDLL, lib_path: str):
        self.name = name
        self.lib = lib
        self.lib_path = lib_path

    def __getattr__(self, sym):
        return getattr(self.lib, sym)

    def custom_op(self, symbol: str, backward_symbol: Optional[str] = None):
        """Wrap `extern "C" void f(const float*, float*, long long)` as a
        unary float op (same-shape output). Backward, if given, has the
        same signature taking the output-cotangent and writing the input-
        cotangent."""
        fwd = getattr(self.lib, symbol)
        fwd.restype = None
        fwd.argtypes = [_F32P, _F32P, ctypes.c_longlong]
        bwd = None
        if backward_symbol is not None:
            bwd = getattr(self.lib, backward_symbol)
            bwd.restype = None
            bwd.argtypes = [_F32P, _F32P, _F32P, ctypes.c_longlong]

        def host_call(x: np.ndarray) -> np.ndarray:
            x = np.ascontiguousarray(x, np.float32)
            out = np.empty_like(x)
            fwd(x.ctypes.data_as(_F32P), out.ctypes.data_as(_F32P), x.size)
            return out

        def op(t):
            t = t if isinstance(t, Tensor) else Tensor(t)
            arr = t.data

            def cb(a):
                return jax.pure_callback(
                    host_call, jax.ShapeDtypeStruct(a.shape, jnp.float32),
                    a, vmap_method="sequential")

            out_arr = cb(arr.astype(jnp.float32))
            out = Tensor(out_arr, stop_gradient=t.stop_gradient or bwd is None)
            if bwd is not None and not t.stop_gradient \
                    and tape_mod.grad_enabled():
                x_host = np.asarray(arr, np.float32)

                def vjp_fn(cotangents):
                    g = np.ascontiguousarray(np.asarray(cotangents[0]),
                                             np.float32)
                    dx = np.empty_like(g)
                    bwd(x_host.ctypes.data_as(_F32P),
                        g.ctypes.data_as(_F32P),
                        dx.ctypes.data_as(_F32P), g.size)
                    return (jnp.asarray(dx),)

                tape_mod.record(vjp_fn, [t], [out], name=f"custom_{symbol}")
            return out

        return op


def load(name: str, sources: Sequence[str], extra_cxx_cflags=None,
         build_directory: Optional[str] = None, verbose: bool = False,
         **kw) -> _LoadedExtension:
    """JIT-compile `sources` into <build_directory>/<name>.so and load it
    (reference cpp_extension.load)."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    flags = list(extra_cxx_cflags or [])
    tag = hashlib.sha1(("\0".join(flags) + "\0" + "".join(
        open(s).read() for s in sources)).encode()).hexdigest()[:12]
    out = os.path.join(build_dir, f"{name}_{tag}.so")
    if not os.path.exists(out):
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", out]
        cmd += list(extra_cxx_cflags or [])
        cmd += [str(s) for s in sources]
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return _LoadedExtension(name, ctypes.CDLL(out), out)


def setup(name: str, ext_modules: List[CppExtension], **kw):
    """setup()-style entry: builds immediately, returns loaded extensions
    (the reference defers to setuptools; TPU custom ops are host callbacks,
    so an eager build is the whole story)."""
    exts = []
    for i, ext in enumerate(ext_modules):
        exts.append(load(f"{name}_{i}" if i else name, ext.sources,
                         extra_cxx_cflags=ext.extra_compile_args))
    return exts[0] if len(exts) == 1 else exts


def get_build_directory() -> str:
    return os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")
