"""paddle.utils parity."""
from . import cpp_extension  # noqa: F401
from .deprecated import deprecated  # noqa: F401


def try_import(module_name: str):
    """reference utils/lazy_import.py."""
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            f"{module_name} is required but not installed") from e


def run_check():
    """reference `paddle.utils.run_check`: verify the install can compute."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    dev = jax.devices()[0]
    print(f"paddle_tpu is installed successfully! device={dev.platform}:"
          f"{dev.id}, matmul checksum={float(y.sum()):.0f}")
    return True
