"""`paddle.utils.deprecated` decorator (reference utils/deprecated.py)."""
from __future__ import annotations

import functools
import warnings


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 1):
    def deco(fn):
        msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f"; use '{update_to}' instead"
        if reason:
            msg += f" ({reason})"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if level > 0:
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco
