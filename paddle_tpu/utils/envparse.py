"""Shared parse helper for ``PADDLE_TPU_*`` environment knobs.

Every numeric knob read in the package goes through this module (the
convention lint in ``paddle_tpu/analysis/conventions.py`` enforces it):
a garbled value — ``PADDLE_TPU_HEALTH_INTERVAL=ten`` — must NEVER
detonate as an anonymous ``int()``/``float()`` ValueError from deep
inside a training step. The PR-5/7 precedent applies everywhere now:

* the default mode **warns once** (naming the knob, the raw value, and
  the default being used) and degrades to the documented default — an
  operator typo does not take down a production job;
* ``strict=True`` raises :class:`EnvKnobError` (a ``ValueError`` that
  names the knob) for the few correctness-critical contracts where a
  silent default would diverge the fleet (the ``coordinator_from_env``
  MASTER_PORT pattern).

``env_bool`` canonicalizes the repo-wide truthiness convention: unset ->
``default``; ``0/false/off/no`` (case-insensitive) -> False; anything
else -> True. Knob names and defaults are documented in the README knob
tables — the convention lint checks every knob referenced in the package
appears there.
"""
from __future__ import annotations

import os
import threading
import warnings
from typing import Optional

__all__ = ["EnvKnobError", "env_int", "env_float", "env_bool", "env_str",
           "FALSEY"]

#: the repo-wide "off" spellings (case-insensitive)
FALSEY = ("0", "false", "off", "no")


class EnvKnobError(ValueError):
    """A PADDLE_TPU_* env knob held an unparseable value (strict mode)."""

    def __init__(self, name: str, raw: str, want: str):
        super().__init__(
            f"{name}={raw!r} is not a valid {want}; unset it or set a "
            f"{want} value")
        self.name = name
        self.raw = raw


# warn once per (knob, raw value): several knobs are re-read per
# construction (EventLog, watchdog) and a garbled value must not spam
_warned: set = set()
_warned_lock = threading.Lock()


def _warn_once(name: str, raw: str, want: str, default):
    key = (name, raw)
    with _warned_lock:
        if key in _warned:
            return
        _warned.add(key)
    warnings.warn(f"{name}={raw!r} is not a valid {want}; "
                  f"using the default ({default})")


def _reset_warned():
    """Test hook: let regression tests assert the warning re-fires."""
    with _warned_lock:
        _warned.clear()


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw string read (empty string counts as unset)."""
    raw = os.environ.get(name, "")
    return raw if raw else default


def env_int(name: str, default: int, *, strict: bool = False) -> int:
    """Integer knob: unset/empty -> default; garbled -> warn + default,
    or EnvKnobError naming the knob under ``strict=True``."""
    raw = os.environ.get(name, "")
    if not raw.strip():
        return default
    try:
        return int(raw)
    except ValueError:
        if strict:
            raise EnvKnobError(name, raw, "integer") from None
        _warn_once(name, raw, "integer", default)
        return default


def env_float(name: str, default: float, *, strict: bool = False) -> float:
    """Float knob: unset/empty -> default; garbled -> warn + default,
    or EnvKnobError naming the knob under ``strict=True``."""
    raw = os.environ.get(name, "")
    if not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        if strict:
            raise EnvKnobError(name, raw, "number") from None
        _warn_once(name, raw, "number", default)
        return default


def env_bool(name: str, default: bool = True) -> bool:
    """Truthiness knob: unset -> default; 0/false/off/no -> False;
    anything else -> True (the repo-wide kill-switch convention)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip().lower() not in FALSEY
