"""Probability distribution base + KL registry.

Reference parity: `python/paddle/distribution/distribution.py:40` (base class),
`python/paddle/distribution/kl.py:32,64` (kl_divergence / register_kl dispatch).
TPU-native: distribution parameters are held as framework Tensors and every
method routes its math through `paddle_tpu.ops._dispatch.call`, so
log_prob/rsample/entropy/kl_divergence all record on the eager autograd tape —
`loss.backward()` reaches the parameters exactly as through any nn op.
`rsample` is reparameterized (pathwise) where the reference's sampler is.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..framework import random as random_mod
from ..framework.tensor import Tensor
from ..ops import _dispatch as _d


def _t(x) -> Tensor:
    """Coerce to a framework Tensor (preserving autograd identity), promoting
    non-float inputs to float32 (distribution params are continuous)."""
    if isinstance(x, Tensor):
        return x
    a = jnp.asarray(x)
    if not (jnp.issubdtype(a.dtype, jnp.floating)
            or jnp.issubdtype(a.dtype, jnp.complexfloating)):
        a = a.astype(jnp.float32)
    return Tensor(a)


def _arr(x, dtype=None):
    """Unwrap to a raw jnp array (no tape) — for shape/dtype inspection and
    non-differentiable paths only."""
    if isinstance(x, Tensor):
        x = x.data
    a = jnp.asarray(x)
    if dtype is None and not (jnp.issubdtype(a.dtype, jnp.floating)
                              or jnp.issubdtype(a.dtype, jnp.complexfloating)):
        a = a.astype(jnp.float32)
    if dtype is not None:
        a = a.astype(dtype)
    return a


def _call(name, impl, *tensors, nondiff=False):
    """Run a pure-array impl through the op tape (phi-kernel equivalent)."""
    return _d.call(impl, tensors, name=name, nondiff=nondiff)


def _wrap(a):
    return Tensor(a) if isinstance(a, jax.Array) else a


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, (int, jnp.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Abstract base (reference `distribution.py:40`)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = _shape_tuple(batch_shape)
        self._event_shape = _shape_tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        """Non-differentiable draw (detached)."""
        out = self.rsample(shape)
        if isinstance(out, Tensor):
            out = out.detach()
        return out

    def rsample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        lp = self.log_prob(value)
        return _call("dist_prob", jnp.exp, lp)

    def probs(self, value):
        return self.prob(value)

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return _shape_tuple(sample_shape) + self._batch_shape + self._event_shape

    def _next_key(self):
        return random_mod.next_key()


# ---------------------------------------------------------------------------
# KL registry (reference kl.py)
# ---------------------------------------------------------------------------
_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a pairwise KL implementation (`kl.py:64`)."""
    if not (issubclass(cls_p, Distribution) and issubclass(cls_q, Distribution)):
        raise TypeError('cls_p and cls_q must be subclass of Distribution')

    def decorator(f):
        _KL_REGISTRY[(cls_p, cls_q)] = f
        _dispatch.cache_clear()  # new entries must be visible to past misses
        return f
    return decorator


@functools.lru_cache(maxsize=None)
def _dispatch(cls_p, cls_q):
    matches = [(p, q) for (p, q) in _KL_REGISTRY
               if issubclass(cls_p, p) and issubclass(cls_q, q)]
    if not matches:
        return None
    # most-derived match wins
    def key(pq):
        p, q = pq
        return (len(p.__mro__), len(q.__mro__))
    return _KL_REGISTRY[max(matches, key=key)]


def kl_divergence(p, q):
    """KL(p || q) via the registered pairwise table (`kl.py:32`)."""
    fn = _dispatch(type(p), type(q))
    if fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return fn(p, q)
