"""paddle.distribution equivalent.

Reference parity: `python/paddle/distribution/__init__.py` — exports the base
class, concrete distributions, transforms, and the KL table.
"""
from .distribution import Distribution, kl_divergence, register_kl
from .distributions import (Beta, Categorical, Dirichlet, ExponentialFamily,
                            Independent, Multinomial, Normal,
                            TransformedDistribution, Uniform)
from .transform import (AbsTransform, AffineTransform, ChainTransform,
                        ExpTransform, IndependentTransform, PowerTransform,
                        ReshapeTransform, SigmoidTransform, SoftmaxTransform,
                        StackTransform, StickBreakingTransform, TanhTransform,
                        Transform, Type)

__all__ = [
    'Distribution', 'ExponentialFamily', 'Normal', 'Uniform', 'Categorical',
    'Multinomial', 'Beta', 'Dirichlet', 'Independent',
    'TransformedDistribution', 'kl_divergence', 'register_kl',
    'Transform', 'Type', 'AbsTransform', 'AffineTransform', 'ChainTransform',
    'ExpTransform', 'IndependentTransform', 'PowerTransform',
    'ReshapeTransform', 'SigmoidTransform', 'SoftmaxTransform',
    'StackTransform', 'StickBreakingTransform', 'TanhTransform',
]
