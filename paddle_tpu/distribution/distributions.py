"""Concrete distributions.

Reference parity: `python/paddle/distribution/{normal,uniform,categorical,
multinomial,beta,dirichlet,exponential_family,independent,
transformed_distribution}.py`. Parameters are framework Tensors; every method
body is a pure-array impl executed through the op-dispatch tape, so gradients
flow to parameters under eager `backward()`. Sampling is reparameterized where
the reference's is (Normal/Uniform/Beta/Dirichlet) — the noise draw is
detached, the pathwise map is on-tape.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma, gammaln, xlogy

from ..framework.tensor import Tensor
from .distribution import (Distribution, _arr, _call, _shape_tuple, _t,
                           _wrap, kl_divergence, register_kl)


class ExponentialFamily(Distribution):
    """Exponential-family base; Bregman-divergence entropy
    (reference `exponential_family.py`). The generic entropy is computed off
    the tape (concrete subclasses override with closed forms that are
    on-tape); it exists for parity + cross-checking."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        # H = A(eta) - <eta, grad A(eta)> - E[carrier]  (Bregman form, as in
        # the reference's ExponentialFamily.entropy autodiff trick)
        nparams = tuple(_arr(p) for p in self._natural_parameters)
        lg = self._log_normalizer(*nparams)
        g = jax.grad(lambda ps: jnp.sum(self._log_normalizer(*ps)))(nparams)
        result = lg - self._mean_carrier_measure
        for np_, g_ in zip(nparams, g):
            result = result - np_ * g_
        return _wrap(result)


class Normal(ExponentialFamily):
    """Gaussian (reference `normal.py`)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        batch = jnp.broadcast_shapes(tuple(self.loc.data.shape),
                                     tuple(self.scale.data.shape))
        super().__init__(batch_shape=batch)

    @property
    def mean(self):
        return _call("normal_mean",
                     lambda loc: jnp.broadcast_to(loc, self.batch_shape),
                     self.loc)

    @property
    def variance(self):
        return _call("normal_variance",
                     lambda s: jnp.broadcast_to(s ** 2, self.batch_shape),
                     self.scale)

    @property
    def stddev(self):
        return _call("normal_stddev",
                     lambda s: jnp.broadcast_to(s, self.batch_shape),
                     self.scale)

    def rsample(self, shape=()):
        shape = self._extend_shape(shape)
        eps = jax.random.normal(self._next_key(), shape,
                                dtype=self.loc.data.dtype)
        return _call("normal_rsample",
                     lambda loc, scale, e: loc + scale * e,
                     self.loc, self.scale, Tensor(eps))

    def log_prob(self, value):
        def impl(loc, scale, v):
            var = scale ** 2
            return (-((v - loc) ** 2) / (2 * var) - jnp.log(scale)
                    - 0.5 * math.log(2 * math.pi))
        return _call("normal_log_prob", impl, self.loc, self.scale, _t(value))

    def entropy(self):
        return _call(
            "normal_entropy",
            lambda s: jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                self.batch_shape),
            self.scale)

    @property
    def _natural_parameters(self):
        loc, scale = _arr(self.loc), _arr(self.scale)
        return (loc / (scale ** 2), -0.5 / (scale ** 2))

    def _log_normalizer(self, x, y):
        return -0.25 * x ** 2 / y + 0.5 * jnp.log(-math.pi / y)


class Uniform(Distribution):
    """U[low, high) (reference `uniform.py`)."""

    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        batch = jnp.broadcast_shapes(tuple(self.low.data.shape),
                                     tuple(self.high.data.shape))
        super().__init__(batch_shape=batch)

    @property
    def mean(self):
        return _call("uniform_mean",
                     lambda lo, hi: jnp.broadcast_to((lo + hi) / 2, self.batch_shape),
                     self.low, self.high)

    @property
    def variance(self):
        return _call("uniform_variance",
                     lambda lo, hi: jnp.broadcast_to((hi - lo) ** 2 / 12, self.batch_shape),
                     self.low, self.high)

    def rsample(self, shape=()):
        shape = self._extend_shape(shape)
        u = jax.random.uniform(self._next_key(), shape,
                               dtype=self.low.data.dtype)
        return _call("uniform_rsample",
                     lambda lo, hi, u_: lo + (hi - lo) * u_,
                     self.low, self.high, Tensor(u))

    def log_prob(self, value):
        def impl(lo, hi, v):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return _call("uniform_log_prob", impl, self.low, self.high, _t(value))

    def entropy(self):
        return _call("uniform_entropy",
                     lambda lo, hi: jnp.broadcast_to(jnp.log(hi - lo), self.batch_shape),
                     self.low, self.high)


class Categorical(Distribution):
    """Categorical over logits (reference `categorical.py`)."""

    def __init__(self, logits=None, probs=None, name=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits/probs")
        if logits is not None:
            self.logits = _t(logits)
            self._from_logits = True
        else:
            self.logits = _t(probs)   # normalized + logged on use
            self._from_logits = False
        super().__init__(batch_shape=tuple(self.logits.data.shape[:-1]))
        self._num_events = self.logits.data.shape[-1]

    def _log_probs_impl(self, raw):
        if self._from_logits:
            return jax.nn.log_softmax(raw, axis=-1)
        p = raw / jnp.sum(raw, axis=-1, keepdims=True)
        return jnp.log(jnp.clip(p, 1e-38, None)) + jnp.log(jnp.sign(p))  # -inf for 0

    @property
    def _log_probs(self):
        """Raw log-prob array (off-tape, for sampling)."""
        return self._log_probs_impl(_arr(self.logits))

    @property
    def probs_param(self):
        return _call("categorical_probs",
                     lambda raw: jnp.exp(self._log_probs_impl(raw)),
                     self.logits)

    def sample(self, shape=()):
        shape = _shape_tuple(shape) + self.batch_shape
        out = jax.random.categorical(self._next_key(), self._log_probs,
                                     axis=-1, shape=shape)
        return _wrap(out)

    def rsample(self, shape=()):
        raise NotImplementedError("Categorical has no reparameterized sample")

    def log_prob(self, value):
        idx = _arr(value, dtype=jnp.int32)

        def impl(raw):
            lp = self._log_probs_impl(raw)
            # value may have lower/higher rank than batch_shape — broadcast both
            out_shape = jnp.broadcast_shapes(idx.shape, lp.shape[:-1])
            lp_b = jnp.broadcast_to(lp, out_shape + (self._num_events,))
            idx_b = jnp.broadcast_to(idx, out_shape)
            return jnp.take_along_axis(lp_b, idx_b[..., None], axis=-1)[..., 0]
        return _call("categorical_log_prob", impl, self.logits)

    def entropy(self):
        def impl(raw):
            lp = self._log_probs_impl(raw)
            p = jnp.exp(lp)
            # xlogy: 0 * log 0 -> 0, so zero-probability atoms contribute 0
            return -jnp.sum(xlogy(p, p), axis=-1)
        return _call("categorical_entropy", impl, self.logits)


class Multinomial(Distribution):
    """Multinomial(total_count, probs) (reference `multinomial.py`)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self._probs_in = _t(probs)
        super().__init__(batch_shape=tuple(self._probs_in.data.shape[:-1]),
                         event_shape=tuple(self._probs_in.data.shape[-1:]))

    @staticmethod
    def _norm(p):
        return p / jnp.sum(p, axis=-1, keepdims=True)

    @property
    def probs(self):
        return _call("multinomial_probs", self._norm, self._probs_in)

    @property
    def mean(self):
        return _call("multinomial_mean",
                     lambda p: self.total_count * self._norm(p), self._probs_in)

    @property
    def variance(self):
        def impl(p):
            pn = self._norm(p)
            return self.total_count * pn * (1 - pn)
        return _call("multinomial_variance", impl, self._probs_in)

    def sample(self, shape=()):
        shape = _shape_tuple(shape) + self.batch_shape
        p = self._norm(_arr(self._probs_in))
        logits = jnp.log(jnp.clip(p, 1e-38, None))
        draws = jax.random.categorical(
            self._next_key(), logits, axis=-1,
            shape=(self.total_count,) + shape)
        onehot = jax.nn.one_hot(draws, p.shape[-1], dtype=p.dtype)
        return _wrap(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        def impl(p, v):
            pn = self._norm(p)
            log_factorial_n = gammaln(jnp.asarray(self.total_count + 1.0))
            log_factorial_xs = jnp.sum(gammaln(v + 1.0), axis=-1)
            return (log_factorial_n - log_factorial_xs
                    + jnp.sum(xlogy(v, pn), axis=-1))
        return _call("multinomial_log_prob", impl, self._probs_in, _t(value))

    def entropy(self):
        raise NotImplementedError


class Beta(ExponentialFamily):
    """Beta(alpha, beta) (reference `beta.py`)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        batch = jnp.broadcast_shapes(tuple(self.alpha.data.shape),
                                     tuple(self.beta.data.shape))
        super().__init__(batch_shape=batch)

    @property
    def mean(self):
        return _call("beta_mean", lambda a, b: a / (a + b),
                     self.alpha, self.beta)

    @property
    def variance(self):
        def impl(a, b):
            s = a + b
            return a * b / (s ** 2 * (s + 1))
        return _call("beta_variance", impl, self.alpha, self.beta)

    def rsample(self, shape=()):
        shape = self._extend_shape(shape)
        key = self._next_key()

        # implicit reparameterization rides jax.random.beta's param grads
        def impl(a, b):
            return jax.random.beta(key,
                                   jnp.broadcast_to(a, self.batch_shape),
                                   jnp.broadcast_to(b, self.batch_shape),
                                   shape=shape)
        return _call("beta_rsample", impl, self.alpha, self.beta)

    def log_prob(self, value):
        def impl(a, b, v):
            return xlogy(a - 1, v) + xlogy(b - 1, 1 - v) - betaln(a, b)
        return _call("beta_log_prob", impl, self.alpha, self.beta, _t(value))

    def entropy(self):
        def impl(a, b):
            return (betaln(a, b) - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                    + (a + b - 2) * digamma(a + b))
        return _call("beta_entropy", impl, self.alpha, self.beta)

    @property
    def _natural_parameters(self):
        return (_arr(self.alpha), _arr(self.beta))

    def _log_normalizer(self, x, y):
        return gammaln(x) + gammaln(y) - gammaln(x + y)

    @property
    def _mean_carrier_measure(self):
        # carrier h(x): E[-log x - log(1-x)] under Beta(a,b)
        a, b = _arr(self.alpha), _arr(self.beta)
        return (digamma(a + b) - digamma(a)) + (digamma(a + b) - digamma(b))


class Dirichlet(ExponentialFamily):
    """Dirichlet(concentration) (reference `dirichlet.py`)."""

    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        shape = tuple(self.concentration.data.shape)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return _call("dirichlet_mean",
                     lambda a: a / jnp.sum(a, axis=-1, keepdims=True),
                     self.concentration)

    @property
    def variance(self):
        def impl(a):
            a0 = jnp.sum(a, axis=-1, keepdims=True)
            m = a / a0
            return m * (1 - m) / (a0 + 1)
        return _call("dirichlet_variance", impl, self.concentration)

    def rsample(self, shape=()):
        batch = _shape_tuple(shape) + self.batch_shape
        key = self._next_key()

        def impl(a):
            return jax.random.dirichlet(key, a, shape=batch)
        return _call("dirichlet_rsample", impl, self.concentration)

    def log_prob(self, value):
        def impl(a, v):
            return (jnp.sum(xlogy(a - 1, v), axis=-1)
                    + gammaln(jnp.sum(a, axis=-1))
                    - jnp.sum(gammaln(a), axis=-1))
        return _call("dirichlet_log_prob", impl, self.concentration, _t(value))

    def entropy(self):
        def impl(a):
            a0 = jnp.sum(a, axis=-1)
            k = a.shape[-1]
            return (jnp.sum(gammaln(a), axis=-1) - gammaln(a0)
                    + (a0 - k) * digamma(a0)
                    - jnp.sum((a - 1) * digamma(a), axis=-1))
        return _call("dirichlet_entropy", impl, self.concentration)


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference `independent.py`)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        if self._rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        shape = base.batch_shape + base.event_shape
        split = len(base.batch_shape) - self._rank
        super().__init__(batch_shape=shape[:split],
                         event_shape=shape[split:])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        if not self._rank:
            return lp
        return _call("independent_log_prob",
                     lambda a: jnp.sum(a, axis=tuple(range(-self._rank, 0))),
                     lp)

    def entropy(self):
        ent = self.base.entropy()
        if not self._rank:
            return ent
        return _call("independent_entropy",
                     lambda a: jnp.sum(a, axis=tuple(range(-self._rank, 0))),
                     ent)


class TransformedDistribution(Distribution):
    """Pushforward of a base through a chain of transforms
    (reference `transformed_distribution.py`)."""

    def __init__(self, base, transforms):
        from .transform import ChainTransform, Transform
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms) if len(self.transforms) != 1 \
            else self.transforms[0]
        # shape-changing transforms (StickBreaking, Reshape) act on event
        # dims: the event rank of the output is the larger of the base's
        # event rank and the chain's event_dim (torch/reference semantics),
        # so e.g. StickBreaking over a batched scalar-event Normal yields a
        # simplex EVENT, not extra batch members
        full = base.batch_shape + base.event_shape
        out_full = tuple(self._chain.forward_shape(full))
        ev = max(len(base.event_shape),
                 getattr(self._chain, "event_dim", 0))
        ev = min(ev, len(out_full))
        split = len(out_full) - ev
        super().__init__(batch_shape=out_full[:split],
                         event_shape=out_full[split:])

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self._chain.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self._chain.forward(x)

    def log_prob(self, value):
        y = _t(value)
        x = self._chain.inverse(y)
        ladj = self._chain.forward_log_det_jacobian(x)
        return self.base.log_prob(x) - ladj


# ---------------------------------------------------------------------------
# Pairwise KL table (reference kl.py registrations) — all on-tape
# ---------------------------------------------------------------------------
@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def impl(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return _call("kl_normal_normal", impl, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def impl(pl, ph, ql, qh):
        result = jnp.log((qh - ql) / (ph - pl))
        return jnp.where((ql <= pl) & (ph <= qh), result, jnp.inf)
    return _call("kl_uniform_uniform", impl, p.low, p.high, q.low, q.high)


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    def impl(praw, qraw):
        plp = p._log_probs_impl(praw)
        qlp = q._log_probs_impl(qraw)
        pp = jnp.exp(plp)
        # 0 * (log 0 - log q) -> 0 via masking zero-support atoms
        diff = jnp.where(pp > 0, plp - qlp, 0.0)
        return jnp.sum(pp * diff, axis=-1)
    return _call("kl_categorical_categorical", impl, p.logits, q.logits)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def impl(pa, pb, qa, qb):
        sp = pa + pb
        sq = qa + qb
        return (gammaln(sp) - gammaln(pa) - gammaln(pb)
                - gammaln(sq) + gammaln(qa) + gammaln(qb)
                + (pa - qa) * digamma(pa)
                + (pb - qb) * digamma(pb)
                + (sq - sp) * digamma(sp))
    return _call("kl_beta_beta", impl, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def impl(a, b):
        a0 = jnp.sum(a, axis=-1)
        return (gammaln(a0) - jnp.sum(gammaln(a), axis=-1)
                - gammaln(jnp.sum(b, axis=-1)) + jnp.sum(gammaln(b), axis=-1)
                + jnp.sum((a - b) * (digamma(a) - digamma(a0)[..., None]),
                          axis=-1))
    return _call("kl_dirichlet_dirichlet", impl, p.concentration,
                 q.concentration)
