"""Bijective transforms for TransformedDistribution.

Reference parity: `python/paddle/distribution/transform.py` (Transform,
AbsTransform, AffineTransform, ChainTransform, ExpTransform,
IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform).
Array-in/array-out core (`*_arr`) + Tensor-facing wrappers; log-det-jacobians
are closed-form (no autodiff in the hot path).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import _arr, _call, _t, _wrap


class Type:
    BIJECTION = 'bijection'
    INJECTION = 'injection'
    SURJECTION = 'surjection'
    OTHER = 'other'


class Transform:
    _type = Type.INJECTION
    # number of rightmost dims this transform operates on as one event
    event_dim = 0

    # -- Tensor-facing API (on the eager autograd tape) ---------------------
    def forward(self, x):
        return _call(f"{type(self).__name__}_fwd", self.forward_arr, _t(x))

    def inverse(self, y):
        return _call(f"{type(self).__name__}_inv", self.inverse_arr, _t(y))

    def forward_log_det_jacobian(self, x):
        return _call(f"{type(self).__name__}_ladj",
                     self.forward_log_det_jacobian_arr, _t(x))

    def inverse_log_det_jacobian(self, y):
        return _call(
            f"{type(self).__name__}_inv_ladj",
            lambda a: -self.forward_log_det_jacobian_arr(self.inverse_arr(a)),
            _t(y))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    # -- array core (override these) ---------------------------------------
    def forward_arr(self, x):
        raise NotImplementedError

    def inverse_arr(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian_arr(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def forward_arr(self, x):
        return jnp.abs(x)

    def inverse_arr(self, y):
        return y  # principal branch, as in the reference

    def forward_log_det_jacobian_arr(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def forward_arr(self, x):
        return self.loc + self.scale * x

    def inverse_arr(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian_arr(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def forward_arr(self, x):
        return jnp.exp(x)

    def inverse_arr(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian_arr(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _arr(power)

    def forward_arr(self, x):
        return jnp.power(x, self.power)

    def inverse_arr(self, y):
        return jnp.power(y, 1.0 / self.power)

    def forward_log_det_jacobian_arr(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def forward_arr(self, x):
        return jax.nn.sigmoid(x)

    def inverse_arr(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian_arr(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def forward_arr(self, x):
        return jnp.tanh(x)

    def inverse_arr(self, y):
        return jnp.arctanh(y)

    def forward_log_det_jacobian_arr(self, x):
        # log|d tanh/dx| = 2 (log2 - x - softplus(-2x)) — numerically stable
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _type = Type.OTHER

    def forward_arr(self, x):
        return jax.nn.softmax(x, axis=-1)

    def inverse_arr(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian_arr(self, x):
        raise NotImplementedError("softmax is not injective")


class StickBreakingTransform(Transform):
    _type = Type.BIJECTION
    event_dim = 1  # maps an R^K vector to a (K+1)-simplex event

    def forward_arr(self, x):
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        z_cumprod = jnp.cumprod(1 - z, axis=-1)
        pad_z = jnp.pad(z, [(0, 0)] * (z.ndim - 1) + [(0, 1)], constant_values=1.0)
        pad_cum = jnp.pad(z_cumprod, [(0, 0)] * (z.ndim - 1) + [(1, 0)],
                          constant_values=1.0)
        return pad_z * pad_cum

    def inverse_arr(self, y):
        # x_k = logit(y_k / (1 - sum_{i<k} y_i)) + log(K - k)
        y_crop = y[..., :-1]
        offset = y_crop.shape[-1] + 1 - jnp.arange(1, y_crop.shape[-1] + 1)
        prev_cum = jnp.concatenate(
            [jnp.zeros_like(y_crop[..., :1]),
             jnp.cumsum(y_crop, axis=-1)[..., :-1]], axis=-1)
        frac = y_crop / jnp.clip(1 - prev_cum, 1e-12, None)
        return (jnp.log(frac) - jnp.log1p(-frac)
                + jnp.log(offset.astype(y.dtype)))

    def forward_log_det_jacobian_arr(self, x):
        # det J = sum_k [ -xo_k + logsigmoid(xo_k) + log y_k ] with
        # xo = x - log(offset); logsigmoid(t) = -softplus(-t)
        y = self.forward_arr(x)[..., :-1]
        offset = x.shape[-1] + 1 - jnp.arange(1, x.shape[-1] + 1)
        xo = x - jnp.log(offset.astype(x.dtype))
        return jnp.sum(-xo - jax.nn.softplus(-xo)
                       + jnp.log(jnp.clip(y, 1e-12, None)), axis=-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self.event_dim = len(self.out_event_shape)
        if int(jnp.prod(jnp.asarray(self.in_event_shape or (1,)))) != \
           int(jnp.prod(jnp.asarray(self.out_event_shape or (1,)))):
            raise ValueError("event sizes must match")

    def forward_arr(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.reshape(x, batch + self.out_event_shape)

    def inverse_arr(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return jnp.reshape(y, batch + self.in_event_shape)

    def forward_log_det_jacobian_arr(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, dtype=x.dtype)


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        self.event_dim = base.event_dim + self._rank

    def forward_arr(self, x):
        return self.base.forward_arr(x)

    def inverse_arr(self, y):
        return self.base.inverse_arr(y)

    def forward_log_det_jacobian_arr(self, x):
        ladj = self.base.forward_log_det_jacobian_arr(x)
        return jnp.sum(ladj, axis=tuple(range(-self._rank, 0)))


class StackTransform(Transform):
    """Apply a list of transforms along slices of an axis."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, method, v):
        parts = jnp.split(v, len(self.transforms), axis=self.axis)
        outs = [getattr(t, method)(jnp.squeeze(p, self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def forward_arr(self, x):
        return self._map('forward_arr', x)

    def inverse_arr(self, y):
        return self._map('inverse_arr', y)

    def forward_log_det_jacobian_arr(self, x):
        return self._map('forward_log_det_jacobian_arr', x)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self.event_dim = max((t.event_dim for t in self.transforms), default=0)

    def forward_arr(self, x):
        for t in self.transforms:
            x = t.forward_arr(x)
        return x

    def inverse_arr(self, y):
        for t in reversed(self.transforms):
            y = t.inverse_arr(y)
        return y

    def forward_log_det_jacobian_arr(self, x):
        total = None
        for t in self.transforms:
            ladj = t.forward_log_det_jacobian_arr(x)
            total = ladj if total is None else total + ladj
            x = t.forward_arr(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape
