"""paddle.hub parity (reference `python/paddle/hub.py`): load models from a
hubconf.py. Zero-egress environment: only `source="local"` works; github
sources raise with guidance."""
from __future__ import annotations

import importlib.util
import os
import sys
from typing import List

HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop("hubconf", None)
    spec.loader.exec_module(mod)
    return mod


def _check_source(source: str):
    if source not in ("local",):
        raise RuntimeError(
            f"source={source!r} needs network access; this environment has "
            f"no egress — clone the repo and use source='local'")


def list(repo_dir: str, source: str = "local", force_reload: bool = False
         ) -> List[str]:
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False) -> str:
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__ or ""


def load(repo_dir: str, model: str, source: str = "local",
         force_reload: bool = False, **kwargs):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"{model!r} not in {repo_dir}/{HUBCONF}; "
                         f"available: {list(repo_dir)}")
    return getattr(mod, model)(**kwargs)
