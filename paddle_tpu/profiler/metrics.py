"""Labeled metrics registry: Counter / Gauge / Histogram with Prometheus
text and JSON-snapshot exporters.

The runtime's quantitative observability spine (complementing the span-based
host tracer in `recorder.py`): op dispatch counts/bytes, jit-cache and
retrace counters, collective bytes by link class (ICI vs DCN), DataLoader
wait time, and device-memory gauges all land here. The reference stack
scatters these over VisualDL scalars and ad-hoc `stat.h` registries
(`paddle/fluid/platform/monitor.h` `Monitor`/`StatRegistry`); on TPU a
single process-wide registry with a `/metrics`-style text dump is the more
useful shape (scrapeable, snapshot-able into bench JSON).

Enable/disable: metrics are ON by default; set `PADDLE_TPU_METRICS=0` (or
call `set_enabled(False)`) to make every instrumentation site skip its
recording. Instrument sites MUST check `metrics.enabled()` so the disabled
path costs one module-attr read.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_registry", "enabled", "set_enabled",
    "update_device_memory_gauges", "sample_device_memory",
]

# default histogram buckets: seconds, spanning sub-ms host dispatch to
# multi-second straggler steps
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_PROM_PREFIX = "paddle_tpu_"


def _label_key(labels: Dict[str, object]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _prom_escape(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _prom_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{_prom_escape(v)}"' for k, v in key) + "}"


class Metric:
    """Base: a named family of label->value series."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], object] = {}

    def clear(self):
        with self._lock:
            self._series.clear()

    def _snapshot_values(self) -> List[dict]:
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in self._series.items()]

    def snapshot(self) -> dict:
        return {"kind": self.kind, "help": self.help,
                "values": self._snapshot_values()}


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels):
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        k = _label_key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value

    def value(self, **labels) -> float:
        return float(self._series.get(_label_key(labels), 0.0))

    def total(self) -> float:
        with self._lock:
            return float(sum(self._series.values()))


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels):
        k = _label_key(labels)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value

    def dec(self, value: float = 1.0, **labels):
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        return float(self._series.get(_label_key(labels), 0.0))


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +inf bucket last
        self.sum = 0.0
        self.count = 0


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, **labels):
        k = _label_key(labels)
        with self._lock:
            s = self._series.get(k)
            if s is None:
                s = self._series[k] = _HistSeries(len(self.buckets))
            i = 0
            for i, b in enumerate(self.buckets):
                if value <= b:
                    break
            else:
                i = len(self.buckets)
            s.counts[i] += 1
            s.sum += value
            s.count += 1

    def _snapshot_values(self) -> List[dict]:
        out = []
        with self._lock:
            for k, s in self._series.items():
                cum, buckets = 0, {}
                for b, c in zip(self.buckets, s.counts):
                    cum += c
                    buckets[repr(b)] = cum
                buckets["+Inf"] = s.count
                out.append({"labels": dict(k), "buckets": buckets,
                            "sum": s.sum, "count": s.count})
        return out


class MetricsRegistry:
    """Process-wide named-metric registry; creation is get-or-create."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, Metric]" = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif type(m) is not cls:
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def reset(self):
        """Zero every series (metric families stay registered)."""
        for m in list(self._metrics.values()):
            m.clear()

    # -- exporters -----------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-serializable {name: {kind, help, values}} snapshot."""
        return {name: self._metrics[name].snapshot()
                for name in self.names()}

    def to_prometheus_text(self) -> str:
        """Prometheus exposition text. Every registered family gets its
        HELP/TYPE header even with no series yet (so scrapers and tests see
        the full metric surface)."""
        lines = []
        for name in self.names():
            m = self._metrics[name]
            full = _PROM_PREFIX + name
            lines.append(f"# HELP {full} {m.help}")
            lines.append(f"# TYPE {full} {m.kind if m.kind != 'untyped' else 'gauge'}")
            if isinstance(m, Histogram):
                for v in m._snapshot_values():
                    base = _label_key(v["labels"])
                    for le, c in v["buckets"].items():
                        k = base + (("le", le),)
                        lines.append(f"{full}_bucket{_prom_labels(k)} {c}")
                    lines.append(f"{full}_sum{_prom_labels(base)} {v['sum']}")
                    lines.append(f"{full}_count{_prom_labels(base)} {v['count']}")
            else:
                for v in m._snapshot_values():
                    k = _label_key(v["labels"])
                    lines.append(f"{full}{_prom_labels(k)} {v['value']}")
        return "\n".join(lines) + "\n"


_default_registry = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default_registry


_enabled = os.environ.get("PADDLE_TPU_METRICS", "1").lower() not in (
    "0", "false", "off", "no")


def enabled() -> bool:
    return _enabled


def set_enabled(flag: bool):
    global _enabled
    _enabled = bool(flag)


def update_device_memory_gauges(registry: Optional[MetricsRegistry] = None
                                ) -> dict:
    """Refresh every device-memory gauge from ONE sampling pass and return
    the sample (see :func:`sample_device_memory` for its shape).

    The PR-2 legacy families (`device_bytes_in_use` /
    `device_peak_bytes_in_use`) are kept as back-compat mirrors of the
    allocator-backed series only (they predate the live-arrays fallback);
    the `device_memory_*` families cover every backend. Honors the
    PADDLE_TPU_METRICS kill switch like every instrument site."""
    if not _enabled:
        return {}
    reg = registry or _default_registry
    sample = sample_device_memory(registry=reg)
    try:
        for label, st in sample.items():
            if st["src"] != "memory_stats":
                continue
            reg.gauge("device_bytes_in_use",
                      "device memory currently allocated").set(
                st["bytes_in_use"], device=label)
            reg.gauge("device_peak_bytes_in_use",
                      "device memory allocation high-water mark").set(
                st["peak_bytes"], device=label)
    except Exception:
        pass
    return sample


# running high-water mark per device label for backends whose allocator
# reports no peak (the live-arrays fallback can only see "now")
_mem_peak_seen: Dict[str, float] = {}


def _live_array_bytes():
    """{device label: bytes} summed over jax.live_arrays() shards — the
    HBM-watermark fallback for backends (CPU) with no memory_stats."""
    import jax
    out: Dict[str, float] = {}
    for a in jax.live_arrays():
        try:
            for sh in a.addressable_shards:
                d = sh.device
                out[f"{d.platform}:{d.id}"] = (
                    out.get(f"{d.platform}:{d.id}", 0.0)
                    + float(getattr(sh.data, "nbytes", 0)))
        except Exception:
            continue
    return out


def sample_device_memory(registry: Optional[MetricsRegistry] = None) -> dict:
    """Sample per-device memory into ``device_memory_bytes_in_use`` /
    ``device_memory_peak_bytes`` gauges and return
    ``{device: {"bytes_in_use", "peak_bytes", "src"}}``.

    Source is the allocator's ``memory_stats()`` where the backend has one
    (TPU/GPU: real HBM watermarks) and a ``jax.live_arrays()`` byte sum
    otherwise (CPU CI: the peak is a running max of samples, so it only
    tightens with sampling frequency). Never raises; honors the
    PADDLE_TPU_METRICS kill switch."""
    if not _enabled:
        return {}
    reg = registry or _default_registry
    out: Dict[str, dict] = {}
    try:
        import jax
        g_use = reg.gauge(
            "device_memory_bytes_in_use",
            "device memory currently allocated, by device "
            "(allocator memory_stats, else live-array byte sum)")
        g_peak = reg.gauge(
            "device_memory_peak_bytes",
            "device memory high-water mark, by device (allocator peak "
            "where available, else running max of samples)")
        live = None
        for d in jax.devices():
            label = f"{d.platform}:{d.id}"
            stats = {}
            try:
                stats = d.memory_stats() or {}
            except Exception:
                pass
            if "bytes_in_use" in stats:
                in_use = float(stats["bytes_in_use"])
                peak = float(stats.get("peak_bytes_in_use", in_use))
                src = "memory_stats"
            else:
                if live is None:
                    live = _live_array_bytes()
                in_use = float(live.get(label, 0.0))
                peak = in_use
                src = "live_arrays"
            peak = max(peak, _mem_peak_seen.get(label, 0.0), in_use)
            _mem_peak_seen[label] = peak
            g_use.set(in_use, device=label)
            g_peak.set(peak, device=label)
            out[label] = {"bytes_in_use": int(in_use),
                          "peak_bytes": int(peak), "src": src}
    except Exception:
        pass
    return out
