"""XLA compile attribution: jax.monitoring events -> entry points.

`jax_log_compiles` only gives stderr lines; this module subscribes to the
same source (`jax.monitoring` duration events, the channel
`jax_log_compiles` feeds) and attributes every trace/lower/compile to the
entry point that triggered it — the retrace watchdog names WHAT changed,
this names WHAT IT COST. Entry points push a thread-local label around the
calls that may compile (`eager:<op>` in ops/_dispatch, `to_static:<fn>` and
`train_step:<layer>` in jit/__init__); compiles observed with no label land
under ``unattributed`` (jax-internal jits, library warmup).

Surfaced three ways:

* metrics: ``xla_compiles_total{entry=}`` (backend compiles) and
  ``xla_compile_seconds{entry=,phase=}`` histograms (phase: trace / lower /
  backend_compile), plus ``xla_compile_cache_events_total{event=}`` from
  jax's persistent compilation cache (hits/misses — the ROADMAP item-5
  signal);
* the retrace watchdog's snapshot gains a ``compiles`` section (count +
  seconds per entry), so one snapshot answers "which entry recompiled and
  what did it cost";
* the unified event log gets one ``xla_compile`` event per backend compile.

Also owns the relaunch-to-first-step clock: `PROCESS_T0` is captured when
`paddle_tpu.profiler` imports (process start for any entry path), and
`note_first_step()` publishes `relaunch_to_first_step_seconds{generation=}`
once — the elastic-relaunch cold-start cost the PR-5 supervisor could not
see.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

from . import metrics as metrics_mod
from . import events as events_mod

__all__ = ["install", "installed", "push_entry", "pop_entry",
           "current_entry", "summary", "reset", "note_first_step",
           "PROCESS_T0"]

#: monotonic clock at profiler import — the relaunch-to-first-step origin
PROCESS_T0 = time.monotonic()

_REG = metrics_mod.default_registry()
# compile durations span ms (tiny eager ops) to minutes (pod-scale steps)
_COMPILE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)
_M_COMPILES = _REG.counter(
    "xla_compiles_total",
    "XLA backend compiles attributed to the entry point that triggered "
    "them (eager:<op> / to_static:<fn> / train_step:<layer> / unattributed)")
_M_COMPILE_SECONDS = _REG.histogram(
    "xla_compile_seconds",
    "jax compile-pipeline durations by entry point and phase "
    "(trace / lower / backend_compile)", buckets=_COMPILE_BUCKETS)
_M_CACHE_EVENTS = _REG.counter(
    "xla_compile_cache_events_total",
    "jax persistent compilation cache events (hits / misses / "
    "compile_requests)")
_M_FIRST_STEP = _REG.gauge(
    "relaunch_to_first_step_seconds",
    "wall time from process start (profiler import) to the first observed "
    "train step, by elastic generation — the relaunch cold-start cost "
    "(import + restore + trace + XLA compile)")

# jax event name -> short phase label
_PHASES = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "backend_compile",
    # older jax spellings (kept so the listener survives version drift)
    "/jax/core/compile/backend_compile_time_duration": "backend_compile",
}
_CACHE_EVENTS = {
    "/jax/compilation_cache/cache_hits": "hit",
    "/jax/compilation_cache/cache_misses": "miss",
    "/jax/compilation_cache/compile_requests_use_cache": "request",
}

_tls = threading.local()
_lock = threading.Lock()
_summary: Dict[str, Dict[str, float]] = {}  # entry -> {count, seconds}
_installed = False
_first_step_noted = False


# -- entry-point labels ------------------------------------------------------
def push_entry(site: str, name: str):
    """Mark the current thread as executing entry `site:name`; returns the
    previous label (pass to pop_entry). Deliberately two attribute ops —
    this sits on the eager dispatch hot path."""
    prev = getattr(_tls, "entry", None)
    _tls.entry = (site, name)
    return prev


def pop_entry(prev):
    _tls.entry = prev


def current_entry() -> str:
    e = getattr(_tls, "entry", None)
    return f"{e[0]}:{e[1]}" if e else "unattributed"


# -- the jax.monitoring listener ---------------------------------------------
def _on_duration(event: str, duration_secs: float, **kw):
    phase = _PHASES.get(event)
    if phase is None:
        return
    try:
        entry = current_entry()
        if metrics_mod.enabled():
            _M_COMPILE_SECONDS.observe(duration_secs, entry=entry,
                                       phase=phase)
        if phase == "backend_compile":
            if metrics_mod.enabled():
                _M_COMPILES.inc(entry=entry)
            with _lock:
                s = _summary.setdefault(entry, {"count": 0, "seconds": 0.0})
                s["count"] += 1
                s["seconds"] += float(duration_secs)
            # feed the retrace watchdog: its snapshot is THE one-stop
            # retrace view, and an XLA recompile without a watchdog event
            # (jax-internal cache miss) must still show up there
            from .watchdog import get_watchdog
            get_watchdog().record_compile(entry, float(duration_secs))
            events_mod.emit("xla_compile", entry=entry,
                            seconds=round(float(duration_secs), 6))
    except Exception:
        pass  # a broken listener must never take down jax compilation


def _on_event(event: str, **kw):
    label = _CACHE_EVENTS.get(event)
    if label is None:
        return
    try:
        if metrics_mod.enabled():
            _M_CACHE_EVENTS.inc(event=label)
    except Exception:
        pass


def install() -> bool:
    """Idempotently register the jax.monitoring listeners. Returns True
    when active (False if this jax has no monitoring API)."""
    global _installed
    if _installed:
        return True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:
        return False
    _installed = True
    return True


def installed() -> bool:
    return _installed


# -- reading -----------------------------------------------------------------
def summary() -> Dict[str, Dict[str, float]]:
    """{entry: {"count": n, "seconds": s}} of backend compiles so far —
    the compile-attribution block bench.py folds into BENCH JSON."""
    with _lock:
        return {k: dict(v) for k, v in _summary.items()}


def reset():
    """Tests only: zero the attribution summary (listeners stay installed)."""
    global _first_step_noted
    with _lock:
        _summary.clear()
    _first_step_noted = False


# -- relaunch-to-first-step --------------------------------------------------
def note_first_step():
    """Publish the relaunch-to-first-step gauge once per process; called by
    the liveness tracker on the first observed step."""
    global _first_step_noted
    if _first_step_noted:
        return
    _first_step_noted = True
    if metrics_mod.enabled():
        gen = os.environ.get("PADDLE_TPU_ELASTIC_RESTART_NUM", "0")
        _M_FIRST_STEP.set(time.monotonic() - PROCESS_T0, generation=gen)
