"""Recompile/retrace watchdog.

On TPU the silent perf killer is the retrace: a shape/dtype/static-arg
change slips into a hot loop and every step pays a fresh trace + XLA
compile. The reference surfaces CUDA-side recompiles through its profiler;
jax surfaces nothing unless you read `jax_log_compiles` stderr. This module
gives the jit entry points (the eager dispatch cache in `ops/_dispatch.py`,
`jit.to_static`, `jit.TrainStep`) one place to report cache lookups, and
turns every NEW abstract signature into a structured `RetraceEvent` naming
the exact delta ("arg0 shape (4, 8)->(6, 8) (dim0 4->6)") against the
previous signature for that site+name.

Opt-in loudness: `PADDLE_TPU_RETRACE_WARN=N` (or `warn_threshold=N`) logs a
warning through the `paddle_tpu.retrace` logger when one site retraces >= N
times inside a window (`reset_window()` is called per epoch by
`ThroughputMonitor`).

Counters mirrored into the metrics registry (`metrics.py`):
`jit_cache_hits_total{site}`, `jit_cache_misses_total{site}`,
`jit_retraces_total{site}`.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Set, Tuple

from . import events as events_mod
from . import metrics as metrics_mod

__all__ = ["RetraceEvent", "RetraceWatchdog", "get_watchdog",
           "describe_delta", "signature_of"]

logger = logging.getLogger("paddle_tpu.retrace")

_REG = metrics_mod.default_registry()
_M_HITS = _REG.counter(
    "jit_cache_hits_total",
    "jit cache lookups that reused a compiled signature, by site")
_M_MISSES = _REG.counter(
    "jit_cache_misses_total",
    "jit cache lookups that required a (re)trace, by site")
_M_RETRACES = _REG.counter(
    "jit_retraces_total",
    "misses whose signature DIFFERS from the site's previous one "
    "(a genuine retrace, not a first compile)")


def _canon_static(v) -> str:
    """Order-insensitive repr of static args: dicts are sorted by key so two
    call sites building the same kwargs in different insertion orders yield
    ONE signature (the eager cache canonicalizes identically via _keyable —
    a mismatch here reported retraces that never compiled)."""
    if isinstance(v, dict):
        return "{" + ", ".join(f"{k!r}: {_canon_static(x)}"
                               for k, x in sorted(v.items(),
                                                  key=lambda kv: repr(kv[0]))) + "}"
    if isinstance(v, (list, tuple)):
        return "(" + ", ".join(_canon_static(x) for x in v) + ")"
    return repr(v)


def signature_of(arrs: Sequence, static=None) -> tuple:
    """Abstract signature: ((shape, dtype) per input, static-args repr).
    Non-array leaves contribute their type name so a python-scalar change
    is still visible."""
    args_sig = []
    for a in arrs:
        shape = getattr(a, "shape", None)
        if shape is not None:
            args_sig.append((tuple(shape), str(getattr(a, "dtype", "?"))))
        else:
            args_sig.append(((), type(a).__name__))
    return (tuple(args_sig), "" if static is None else _canon_static(static))


def describe_delta(old: tuple, new: tuple) -> str:
    """Human/grep-able description of what changed between two signatures."""
    parts = []
    (oa, ostatic), (na, nstatic) = old, new
    if len(oa) != len(na):
        parts.append(f"arity {len(oa)}->{len(na)}")
    else:
        for i, ((osh, odt), (nsh, ndt)) in enumerate(zip(oa, na)):
            if osh != nsh:
                if len(osh) == len(nsh):
                    dims = ", ".join(f"dim{j} {osh[j]}->{nsh[j]}"
                                     for j in range(len(osh))
                                     if osh[j] != nsh[j])
                    parts.append(f"arg{i} shape {osh}->{nsh} ({dims})")
                else:
                    parts.append(f"arg{i} rank {len(osh)}->{len(nsh)} "
                                 f"({osh}->{nsh})")
            if odt != ndt:
                parts.append(f"arg{i} dtype {odt}->{ndt}")
    if ostatic != nstatic:
        parts.append(f"static args {ostatic or '()'}->{nstatic or '()'}")
    return "; ".join(parts) or "signature changed"


@dataclass
class RetraceEvent:
    """One observed retrace: site ('eager'|'to_static'|'train_step'),
    callable/op name, per-site+name retrace count, and the signature delta
    that triggered it."""
    site: str
    name: str
    count: int            # retraces of this site+name since process start
    window_count: int     # retraces since the last reset_window() (epoch)
    delta: str
    signature: tuple
    ts_ns: int = field(default_factory=time.perf_counter_ns)

    def to_dict(self) -> dict:
        return {"site": self.site, "name": self.name, "count": self.count,
                "window_count": self.window_count, "delta": self.delta,
                "ts_ns": self.ts_ns}


class RetraceWatchdog:
    _SEEN_MAX = 4096  # signatures remembered per (site, name)

    def __init__(self, history: int = 256,
                 warn_threshold: Optional[int] = None):
        self._lock = threading.Lock()
        self._seen: Dict[Tuple[str, str], Set[tuple]] = {}
        self._last: Dict[Tuple[str, str], tuple] = {}
        self._retraces: Dict[Tuple[str, str], int] = {}
        self._window: Dict[Tuple[str, str], int] = {}
        self._warned: Set[Tuple[str, str]] = set()
        self._compiles: Dict[str, Dict[str, float]] = {}
        self.events: "deque[RetraceEvent]" = deque(maxlen=history)
        if warn_threshold is None:
            from ..utils.envparse import env_int
            warn_threshold = env_int("PADDLE_TPU_RETRACE_WARN", 0)
        self.warn_threshold = warn_threshold

    # -- recording -----------------------------------------------------------
    def observe(self, site: str, name: str, arrs: Sequence = (),
                static=None, signature: Optional[tuple] = None,
                count_hit: bool = True) -> Optional[RetraceEvent]:
        """Report one jit-cache lookup. Returns a RetraceEvent iff this is a
        NEW signature for a site+name that already compiled a different one.
        `count_hit=False` suppresses the hit counter for callers (the eager
        dispatch cache) that count their own hits and only report misses."""
        sig = signature if signature is not None else signature_of(arrs, static)
        key = (site, name)
        m_on = metrics_mod.enabled()
        with self._lock:
            seen = self._seen.setdefault(key, set())
            if sig in seen:
                if count_hit and m_on:
                    _M_HITS.inc(site=site)
                return None
            # bound per-site+name memory: a workload with endlessly varying
            # shapes (the exact case the watchdog diagnoses) must not grow
            # this set forever — restart dedup when full (a few subsequent
            # re-sighted signatures count as misses again; acceptable)
            if len(seen) >= self._SEEN_MAX:
                seen.clear()
            seen.add(sig)
            last = self._last.get(key)
            self._last[key] = sig
            if m_on:
                _M_MISSES.inc(site=site)
            if last is None:
                return None  # first compile, nothing to diff
            count = self._retraces[key] = self._retraces.get(key, 0) + 1
            wcount = self._window[key] = self._window.get(key, 0) + 1
            event = RetraceEvent(site=site, name=name, count=count,
                                 window_count=wcount,
                                 delta=describe_delta(last, sig),
                                 signature=sig)
            self.events.append(event)
            warn = (self.warn_threshold > 0
                    and wcount >= self.warn_threshold
                    and key not in self._warned)
            if warn:
                self._warned.add(key)
        if m_on:
            _M_RETRACES.inc(site=site)
        events_mod.emit("retrace", site=site, name=name, count=count,
                        delta=event.delta)
        logger.debug("retrace %s:%s #%d — %s", site, name, event.count,
                     event.delta)
        if warn:
            logger.warning(
                "[paddle_tpu] %s %r retraced %d times in one window "
                "(last delta: %s) — varying shapes/dtypes/static args force "
                "a fresh XLA compile each time; pad or bucket the inputs "
                "(threshold PADDLE_TPU_RETRACE_WARN=%d)",
                site, name, wcount, event.delta, self.warn_threshold)
        return event

    def record_compile(self, entry: str, seconds: float):
        """One XLA backend compile attributed to `entry` (fed by
        profiler/compile_watch.py's jax.monitoring listener) — so the
        watchdog snapshot pairs WHAT retraced with what the recompiles
        actually COST."""
        with self._lock:
            s = self._compiles.setdefault(entry,
                                          {"count": 0, "seconds": 0.0})
            s["count"] += 1
            s["seconds"] += float(seconds)

    # -- reading -------------------------------------------------------------
    def total_retraces(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is None:
                return sum(self._retraces.values())
            return sum(v for (s, _), v in self._retraces.items()
                       if s == site)

    def counts(self) -> Dict[str, int]:
        """{'site:name': retrace count} for everything that retraced."""
        with self._lock:
            return {f"{s}:{n}": c for (s, n), c in self._retraces.items()}

    def snapshot(self) -> dict:
        with self._lock:
            events = [e.to_dict() for e in self.events]
            compiles = {k: dict(v) for k, v in self._compiles.items()}
        return {"total_retraces": self.total_retraces(),
                "by_site_name": self.counts(), "events": events,
                "compiles": compiles}

    # -- lifecycle -----------------------------------------------------------
    def reset_window(self):
        """Start a new warn window (per epoch, from ThroughputMonitor)."""
        with self._lock:
            self._window.clear()
            self._warned.clear()

    def reset(self):
        """Full reset (tests)."""
        with self._lock:
            self._seen.clear()
            self._last.clear()
            self._retraces.clear()
            self._window.clear()
            self._warned.clear()
            self._compiles.clear()
            self.events.clear()


_watchdog = RetraceWatchdog()


def get_watchdog() -> RetraceWatchdog:
    return _watchdog
