"""Sliding-window SLO tracker for the serving plane.

ROADMAP item 2's controller policies ("shed/queue on p99 TTFT breach,
restart a wedged engine") need a signal that says *the serving SLO is
breached* — not a raw histogram. This module keeps sliding windows of
the four user-facing serving latencies —

    ttft        time to first token (s)
    tpot        time per output token (s)
    queue_wait  admission-queue wait (s)
    e2e         submit -> done wall time (s)

— computes window p50/p95/p99, and holds them against operator targets.
A target excursion emits exactly ONE `slo_breach` structured event and
then re-arms when the window recovers (the same transition shape as the
PR-9 health detector and the fleet straggler detector: state on entry,
pop on recovery — never one event per sample). Current status is
mirrored into the fleet digest (`serving_slo` field) so the controller
direction can consume serving health exactly like trainer health.

Knobs (envparse'd; documented in README "Serving observability"):

    PADDLE_TPU_SLO=0                kill switch (observe/check no-ops)
    PADDLE_TPU_SLO_WINDOW=512       samples kept per signal
    PADDLE_TPU_SLO_MIN_SAMPLES=8    samples required before checking
    PADDLE_TPU_SLO_TTFT_P99_S       p99 TTFT target, seconds
    PADDLE_TPU_SLO_TPOT_P99_S       p99 TPOT target, seconds
    PADDLE_TPU_SLO_QUEUE_P99_S     p99 queue-wait target, seconds
    PADDLE_TPU_SLO_E2E_P99_S        p99 e2e-latency target, seconds

Unset targets are simply not checked — the tracker still serves window
quantiles on `/slo` for whatever signals it observed.
"""
from __future__ import annotations

import threading
import weakref
from collections import deque
from typing import Dict, List, Optional

from ..utils.envparse import env_bool, env_float, env_int
from . import events as _events
from . import metrics as _metrics

__all__ = ["SLOTracker", "SIGNALS", "QUANTILES", "enabled",
           "default_targets", "last_status", "current_snapshot"]

SIGNALS = ("ttft", "tpot", "queue_wait", "e2e", "handoff_wait")
QUANTILES = ("p50", "p95", "p99")

_REG = _metrics.default_registry()
_M_BREACHES = _REG.counter(
    "slo_breaches_total",
    "slo_breach excursions (one per entry, re-armed on recovery), "
    "by model and signal")
_M_BREACHED = _REG.gauge(
    "slo_breached",
    "1 while the signal's window p99 exceeds its target else 0, "
    "by model and signal")
_M_P99 = _REG.gauge(
    "slo_window_p99_seconds",
    "sliding-window p99 of the serving signal, by model and signal")


def enabled() -> bool:
    """Kill switch: PADDLE_TPU_SLO=0 disables observation and checking."""
    return env_bool("PADDLE_TPU_SLO", True)


def default_targets() -> Dict[str, float]:
    """p99 targets from the PADDLE_TPU_SLO_* knobs; unset -> unchecked."""
    out: Dict[str, float] = {}
    pairs = (("ttft", env_float("PADDLE_TPU_SLO_TTFT_P99_S", 0.0)),
             ("tpot", env_float("PADDLE_TPU_SLO_TPOT_P99_S", 0.0)),
             ("queue_wait", env_float("PADDLE_TPU_SLO_QUEUE_P99_S", 0.0)),
             ("e2e", env_float("PADDLE_TPU_SLO_E2E_P99_S", 0.0)),
             ("handoff_wait",
              env_float("PADDLE_TPU_SLO_HANDOFF_P99_S", 0.0)))
    for sig, t in pairs:
        if t > 0:
            out[sig] = t
    return out


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated quantile of an already-sorted sample list."""
    n = len(sorted_vals)
    if n == 1:
        return sorted_vals[0]
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


class SLOTracker:
    """Sliding windows + breach detection for one serving engine.

    `observe()` is the hot-path entry (called per request completion /
    first token); `snapshot()` is the `/slo` endpoint payload. Breach
    state is per signal: enter -> ONE `slo_breach` event + counter inc,
    leave -> re-arm silently (gauge drops back to 0).
    """

    def __init__(self, model: str = "gpt", *,
                 window: Optional[int] = None,
                 min_samples: Optional[int] = None,
                 targets: Optional[Dict[str, float]] = None):
        self.model = model
        self.window = max(1, env_int("PADDLE_TPU_SLO_WINDOW", 512)
                          if window is None else int(window))
        self.min_samples = max(1, env_int("PADDLE_TPU_SLO_MIN_SAMPLES", 8)
                               if min_samples is None else int(min_samples))
        self.targets = dict(default_targets() if targets is None
                            else targets)
        self._lock = threading.Lock()
        self._windows: Dict[str, deque] = {
            s: deque(maxlen=self.window) for s in SIGNALS}
        #: signal -> breach record while breached; absent = armed
        self._breached: Dict[str, dict] = {}
        self.stats = {"breaches": 0, "recoveries": 0, "observations": 0}
        global _current
        _current = weakref.ref(self)

    # -- observation ---------------------------------------------------------
    def observe(self, signal: str, value: float):
        if not enabled():
            return
        if signal not in self._windows:
            raise ValueError(f"unknown SLO signal {signal!r}; "
                             f"expected one of {SIGNALS}")
        with self._lock:
            self._windows[signal].append(float(value))
            self.stats["observations"] += 1
            self._check_locked(signal)

    def quantiles(self, signal: str) -> dict:
        with self._lock:
            return self._quantiles_locked(signal)

    def _quantiles_locked(self, signal: str) -> dict:
        vals = sorted(self._windows[signal])
        out = {"count": len(vals)}
        if not vals:
            out.update({q: None for q in QUANTILES})
            return out
        out["p50"] = _quantile(vals, 0.50)
        out["p95"] = _quantile(vals, 0.95)
        out["p99"] = _quantile(vals, 0.99)
        return out

    # -- breach detection (one event per excursion, re-arm on recovery) ------
    def _check_locked(self, signal: str):
        target = self.targets.get(signal)
        if target is None:
            return
        qs = self._quantiles_locked(signal)
        if qs["count"] < self.min_samples:
            return
        p99 = qs["p99"]
        if _metrics.enabled():
            _M_P99.set(p99, model=self.model, signal=signal)
        if p99 > target:
            if signal not in self._breached:
                self._breached[signal] = {
                    "signal": signal, "quantile": "p99",
                    "value": p99, "target": target,
                    "window": qs["count"]}
                self.stats["breaches"] += 1
                if _metrics.enabled():
                    _M_BREACHES.inc(model=self.model, signal=signal)
                    _M_BREACHED.set(1, model=self.model, signal=signal)
                _events.emit("slo_breach", severity="warn",
                             model=self.model, signal=signal,
                             quantile="p99", value=p99, target=target,
                             window=qs["count"])
            else:
                # still breached: refresh the live excursion value only
                self._breached[signal]["value"] = p99
        elif signal in self._breached:
            self._breached.pop(signal, None)
            self.stats["recoveries"] += 1
            if _metrics.enabled():
                _M_BREACHED.set(0, model=self.model, signal=signal)

    # -- views ---------------------------------------------------------------
    def breached(self) -> Dict[str, dict]:
        with self._lock:
            return {s: dict(b) for s, b in self._breached.items()}

    def status(self) -> str:
        """'ok' | 'breach:<signal,...>' — the fleet-digest mirror value."""
        with self._lock:
            if not self._breached:
                return "ok"
            return "breach:" + ",".join(sorted(self._breached))

    def snapshot(self) -> dict:
        """`/slo` endpoint payload: targets, window quantiles per signal,
        and current breach status."""
        with self._lock:
            return {
                "enabled": enabled(),
                "model": self.model,
                "window": self.window,
                "min_samples": self.min_samples,
                "targets": dict(self.targets),
                "signals": {s: self._quantiles_locked(s) for s in SIGNALS},
                "breached": {s: dict(b)
                             for s, b in self._breached.items()},
                "status": ("ok" if not self._breached else
                           "breach:" + ",".join(sorted(self._breached))),
                "stats": dict(self.stats),
            }


#: weakref to the most recently constructed tracker — what the fleet
#: digest and a tracker-less ObservabilityServer read.
_current: Optional["weakref.ref[SLOTracker]"] = None


def _current_tracker() -> Optional[SLOTracker]:
    ref = _current
    return ref() if ref is not None else None


def last_status() -> Optional[str]:
    """Status of the live tracker ('ok' / 'breach:...'), None if no
    serving engine has constructed one — the `FleetReporter.digest()`
    mirror, shaped like profiler.health.last_status()."""
    t = _current_tracker()
    return t.status() if t is not None else None


def current_snapshot() -> Optional[dict]:
    t = _current_tracker()
    return t.snapshot() if t is not None else None
