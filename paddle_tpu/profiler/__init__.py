"""paddle.profiler equivalent.

Reference parity: `python/paddle/profiler/` (`profiler.py:262` Profiler,
`profiler.py:65` make_scheduler, `profiler.py:152` export_chrome_tracing,
`utils.py:31` RecordEvent, `timer.py:325` Benchmark/ips). TPU-native: host
spans are recorded by our own lightweight recorder (the reference's
HostEventRecorder, `platform/profiler/host_event_recorder.h`) and exported as
chrome://tracing JSON; device-side tracing delegates to `jax.profiler`
(XPlane/TensorBoard), the TPU answer to CUPTI.
"""
from . import compile_watch
from . import device_time
from . import events
from . import health
from .health import HealthMonitor
from . import metrics
from .monitor import (ThroughputMonitor, make_step_record,
                      validate_step_record)
from . import server
from . import xplane
from .profiler import (Profiler, ProfilerState, ProfilerTarget,
                       export_chrome_tracing, export_protobuf, make_scheduler)
from .statistic import SortedKeys, StatisticData, summary_report
from .timer import Benchmark, benchmark
from .utils import RecordEvent, load_profiler_result
from .watchdog import RetraceWatchdog, get_watchdog

# subscribe to jax's compile-event stream at import so every XLA compile in
# the process — including jit warmup before any entry point runs — is
# attributed (listener cost is nanoseconds per compile event)
compile_watch.install()

__all__ = [
    'Profiler', 'ProfilerState', 'ProfilerTarget', 'make_scheduler',
    'export_chrome_tracing', 'export_protobuf', 'RecordEvent',
    'load_profiler_result', 'SortedKeys', 'StatisticData', 'summary_report',
    'Benchmark', 'benchmark', 'metrics', 'events', 'compile_watch',
    'device_time', 'health', 'server', 'xplane', 'ThroughputMonitor',
    'HealthMonitor', 'make_step_record', 'validate_step_record',
    'RetraceWatchdog', 'get_watchdog',
]
