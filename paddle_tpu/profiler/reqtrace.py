"""Request-scoped serving traces: per-request lifecycle spans for the
continuous-batching engine.

The serving stack reports aggregate `serving_ttft/tpot` histograms, but
once a request enters the decode loop its queue wait, prefill, per-
iteration decode, and preemptions are invisible. This module is the
per-request signal plane: the ServingEngine calls into a
:class:`RequestTracer` at each lifecycle transition and the tracer
records spans —

    queued -> admitted -> prefill (shared-prefix skip noted)
           -> decode (bucketed per N iterations, labeled bucket/path)
           -> preempt/requeue (SAME trace id across the re-prefill)
           -> complete | failed

— into a bounded ring of completed traces, exportable as chrome-trace
JSON (``chrome://tracing`` / Perfetto) and JSONL. Per-phase durations
feed three histogram families the aggregate plane was missing:
`serving_queue_wait_seconds`, `serving_prefill_seconds`, and
`serving_preempt_requeue_seconds`.

Knobs (all envparse'd, all documented in README):

    PADDLE_TPU_REQTRACE=0          kill switch: every hook is a no-op
    PADDLE_TPU_REQTRACE_RING=256   completed traces kept in memory
    PADDLE_TPU_REQTRACE_EVERY=8    decode-iteration span bucketing: one
                                   `decode` span per N iterations
    PADDLE_TPU_REQTRACE_LOG=path   append one JSON line per completed
                                   trace (the obs_tail/offline input)

Each completed trace also emits ONE `request_trace` structured event
(registered in events.KIND_SEVERITY) carrying the phase breakdown, so
`/events?kind=request_trace` and bench JSON see per-request latency
attribution without scraping the ring.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..utils.envparse import env_bool, env_int, env_str
from . import events as _events
from . import metrics as _metrics

__all__ = ["RequestTracer", "Trace", "default_tracer", "enabled",
           "to_chrome_trace", "PHASES"]

#: canonical lifecycle phase names, in order of first appearance
PHASES = ("queued", "prefill", "decode", "preempted", "complete", "failed")

_REG = _metrics.default_registry()
_M_QWAIT = _REG.histogram(
    "serving_queue_wait_seconds",
    "seconds a request waited in the admission queue before prefill, "
    "by model; re-admissions after preemption observe again")
_M_PREFILL = _REG.histogram(
    "serving_prefill_seconds",
    "prefill (prompt ingestion) seconds per admission, by model")
_M_REQUEUE = _REG.histogram(
    "serving_preempt_requeue_seconds",
    "seconds between a preemption and the request's re-admission "
    "(recompute requeue wait), by model")

_trace_ids = itertools.count(1)


def enabled() -> bool:
    """Kill switch: PADDLE_TPU_REQTRACE=0 disables every tracer hook."""
    return env_bool("PADDLE_TPU_REQTRACE", True)


class Trace:
    """One request's lifecycle: an ordered list of spans sharing one id.

    A span is ``{"phase", "start", "end", ...labels}`` with monotonic
    timestamps; ``end`` is None while the span is open. The SAME Trace
    object (and trace id) survives preemption + re-prefill.
    """

    __slots__ = ("trace_id", "rid", "model", "submitted_ts", "done_ts",
                 "spans", "state", "finish_reason", "preemptions",
                 "decode_iterations", "decode_tokens", "shared_tokens")

    def __init__(self, trace_id: int, rid: int, model: str):
        self.trace_id = trace_id
        self.rid = rid
        self.model = model
        self.submitted_ts = time.monotonic()
        self.done_ts: Optional[float] = None
        self.spans: List[dict] = []
        self.state = "queued"
        self.finish_reason: Optional[str] = None
        self.preemptions = 0
        self.decode_iterations = 0
        self.decode_tokens = 0
        self.shared_tokens = 0

    # -- span plumbing -------------------------------------------------------
    def open_span(self, phase: str, **labels) -> dict:
        span = {"phase": phase, "start": time.monotonic(), "end": None}
        span.update(labels)
        self.spans.append(span)
        return span

    def close_span(self, phase: Optional[str] = None) -> Optional[dict]:
        """Close the most recent open span (optionally of `phase`)."""
        for span in reversed(self.spans):
            if span["end"] is None and (phase is None
                                        or span["phase"] == phase):
                span["end"] = time.monotonic()
                return span
        return None

    def open_spans(self) -> List[dict]:
        return [s for s in self.spans if s["end"] is None]

    # -- derived views -------------------------------------------------------
    def phase_durations(self) -> Dict[str, float]:
        """Total seconds per phase (closed spans only)."""
        out: Dict[str, float] = {}
        for s in self.spans:
            if s["end"] is not None:
                out[s["phase"]] = out.get(s["phase"], 0.0) \
                    + (s["end"] - s["start"])
        return out

    def e2e_s(self) -> Optional[float]:
        if self.done_ts is None:
            return None
        return self.done_ts - self.submitted_ts

    def to_dict(self) -> dict:
        """JSON-serializable trace record (the JSONL line shape)."""
        return {
            "trace_id": self.trace_id,
            "rid": self.rid,
            "model": self.model,
            "state": self.state,
            "finish_reason": self.finish_reason,
            "preemptions": self.preemptions,
            "decode_iterations": self.decode_iterations,
            "decode_tokens": self.decode_tokens,
            "shared_tokens": self.shared_tokens,
            "e2e_s": self.e2e_s(),
            "phases": self.phase_durations(),
            "spans": [dict(s) for s in self.spans],
        }


class RequestTracer:
    """Assigns trace ids and records lifecycle spans for serving requests.

    The engine owns one tracer; every hook is cheap (dict/list ops under
    one lock) and a no-op when the kill switch is off. Completed traces
    land in a bounded ring; live traces are keyed by request id.
    """

    def __init__(self, model: str = "gpt", *,
                 ring: Optional[int] = None,
                 decode_every: Optional[int] = None,
                 log_path: Optional[str] = None):
        self.model = model
        self._ring_size = (env_int("PADDLE_TPU_REQTRACE_RING", 256)
                           if ring is None else int(ring))
        self.decode_every = max(1, env_int("PADDLE_TPU_REQTRACE_EVERY", 8)
                                if decode_every is None else int(decode_every))
        self._log_path = (env_str("PADDLE_TPU_REQTRACE_LOG")
                          if log_path is None else log_path)
        self._lock = threading.Lock()
        self._live: Dict[int, Trace] = {}
        self._done: "deque[Trace]" = deque(maxlen=max(1, self._ring_size))

    # -- lifecycle hooks (called by ServingEngine) ---------------------------
    def submit(self, rid: int) -> Optional[int]:
        """Request entered the admission queue; opens the `queued` span
        and returns the assigned trace id (None when disabled)."""
        if not enabled():
            return None
        with self._lock:
            tr = Trace(next(_trace_ids), rid, self.model)
            tr.open_span("queued")
            self._live[rid] = tr
            return tr.trace_id

    def admitted(self, rid: int, *, bucket: int, prompt_tokens: int,
                 shared_tokens: int = 0, requeue: bool = False):
        """Queue wait ended, prefill starts. `requeue=True` marks a
        re-admission after preemption: the re-prefill span is labeled
        and the requeue wait feeds its own histogram family."""
        tr = self._live.get(rid)
        if tr is None:
            return
        with self._lock:
            now = time.monotonic()
            span = tr.close_span("preempted" if requeue else "queued")
            wait = (now - span["start"]) if span else 0.0
            if _metrics.enabled():
                if requeue:
                    _M_REQUEUE.observe(wait, model=self.model)
                else:
                    _M_QWAIT.observe(wait, model=self.model)
            tr.state = "running"
            tr.shared_tokens = max(tr.shared_tokens, int(shared_tokens))
            labels = {"bucket": int(bucket),
                      "prompt_tokens": int(prompt_tokens)}
            if shared_tokens:
                labels["shared_prefix_skip"] = int(shared_tokens)
            if requeue:
                labels["requeue"] = True
            tr.open_span("prefill", **labels)

    def prefill_done(self, rid: int):
        tr = self._live.get(rid)
        if tr is None:
            return
        with self._lock:
            span = tr.close_span("prefill")
            if span is not None and _metrics.enabled():
                _M_PREFILL.observe(span["end"] - span["start"],
                                   model=self.model)

    def decode_iteration(self, rid: int, *, bucket: int, path: str,
                         tokens: int = 1):
        """One decode iteration for this request. Spans are bucketed:
        a `decode` span stays open across `decode_every` iterations (or
        until the bucket/path labels change) to bound span count."""
        tr = self._live.get(rid)
        if tr is None:
            return
        with self._lock:
            tr.decode_iterations += 1
            tr.decode_tokens += int(tokens)
            now = time.monotonic()
            cur = None
            for s in reversed(tr.spans):
                if s["phase"] == "decode" and s["end"] is None:
                    cur = s
                    break
            if cur is not None and (cur["bucket"] != int(bucket)
                                    or cur["path"] != path
                                    or cur["iters"] >= self.decode_every):
                cur["end"] = now
                cur = None
            if cur is None:
                span = tr.open_span("decode", bucket=int(bucket),
                                    path=path, iters=1)
                # contiguous attribution: a decode span starts where the
                # previous closed span (prefill or the prior decode
                # bucket) ended, so in-batch wait between a request's
                # prefill and its first decode dispatch — time spent
                # waiting on OTHER lanes' prefills — is charged to
                # decode and per-phase durations sum to the e2e wall
                prev_end = max((s["end"] for s in tr.spans
                                if s["end"] is not None), default=None)
                if prev_end is not None and prev_end < span["start"]:
                    span["start"] = prev_end
            else:
                cur["iters"] += 1

    def preempted(self, rid: int):
        """Request was evicted back to the queue (recompute preemption).
        The trace id is KEPT; a `preempted` span stays open until the
        re-admission closes it into serving_preempt_requeue_seconds."""
        tr = self._live.get(rid)
        if tr is None:
            return
        with self._lock:
            for s in tr.open_spans():
                s["end"] = time.monotonic()
            tr.preemptions += 1
            tr.state = "queued"
            tr.open_span("preempted")

    def complete(self, rid: int, reason: str, *,
                 error: Optional[str] = None):
        """Terminal transition: closes every open span, records the
        complete/failed marker span, moves the trace to the ring, emits
        one `request_trace` event, and appends the JSONL line."""
        tr = self._live.pop(rid, None)
        if tr is None:
            return
        with self._lock:
            now = time.monotonic()
            for s in tr.open_spans():
                s["end"] = now
            tr.done_ts = now
            failed = reason == "error" or error is not None
            tr.state = "failed" if failed else "complete"
            tr.finish_reason = reason
            marker = tr.open_span("failed" if failed else "complete")
            if error:
                marker["error"] = str(error)
            marker["start"] = marker["end"] = now  # zero-width marker
            self._done.append(tr)
        rec = tr.to_dict()
        _events.emit("request_trace",
                     severity="warn" if failed else "info",
                     trace_id=tr.trace_id, rid=tr.rid, model=self.model,
                     finish_reason=reason, preemptions=tr.preemptions,
                     decode_tokens=tr.decode_tokens,
                     e2e_s=rec["e2e_s"], phases=rec["phases"])
        if self._log_path:
            try:
                with open(self._log_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                pass

    # -- views ---------------------------------------------------------------
    def get(self, rid: int) -> Optional[Trace]:
        tr = self._live.get(rid)
        if tr is not None:
            return tr
        with self._lock:
            for t in self._done:
                if t.rid == rid:
                    return t
        return None

    def live(self) -> List[dict]:
        with self._lock:
            return [t.to_dict() for t in self._live.values()]

    def completed(self, n: int = 50) -> List[dict]:
        with self._lock:
            return [t.to_dict() for t in list(self._done)[-max(0, n):]]

    def snapshot(self, n: int = 50) -> dict:
        """Endpoint/bench-serializable view: live + recently completed."""
        return {
            "enabled": enabled(),
            "model": self.model,
            "live": self.live(),
            "completed": self.completed(n),
            "ring_size": self._ring_size,
            "decode_every": self.decode_every,
        }

    def export_jsonl(self, path: str, n: Optional[int] = None) -> int:
        """Write completed traces (oldest first) as JSONL; returns count."""
        recs = self.completed(n if n is not None else self._ring_size)
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return len(recs)

    def export_chrome_trace(self, path: str,
                            n: Optional[int] = None) -> int:
        recs = self.completed(n if n is not None else self._ring_size)
        with open(path, "w") as f:
            json.dump(to_chrome_trace(recs), f)
        return len(recs)


def to_chrome_trace(traces: List[dict]) -> dict:
    """Convert trace dicts to the chrome://tracing JSON object format:
    one pid per model, one tid per trace id, complete ("X") events per
    span with phase labels in args."""
    tevents = []
    for t in traces:
        for s in t.get("spans", ()):
            if s.get("end") is None:
                continue
            args = {k: v for k, v in s.items()
                    if k not in ("phase", "start", "end")}
            args["trace_id"] = t["trace_id"]
            args["rid"] = t["rid"]
            tevents.append({
                "name": s["phase"],
                "ph": "X",
                "pid": t.get("model", "serving"),
                "tid": t["trace_id"],
                "ts": s["start"] * 1e6,
                "dur": (s["end"] - s["start"]) * 1e6,
                "args": args,
            })
    return {"traceEvents": tevents, "displayTimeUnit": "ms"}


_default_tracer: Optional[RequestTracer] = None
_default_lock = threading.Lock()


def default_tracer(model: str = "gpt") -> RequestTracer:
    """Process-default tracer (the one endpoints read when no engine is
    registered); engines normally construct their own."""
    global _default_tracer
    with _default_lock:
        if _default_tracer is None:
            _default_tracer = RequestTracer(model)
        return _default_tracer
