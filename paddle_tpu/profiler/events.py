"""Unified structured event log: one JSONL stream for every runtime event.

PR 2 gave each subsystem its own event shape (watchdog RetraceEvents,
fault-injection warnings, barrier abort warnings, elastic restart
warnings...) — operable only by grepping five different log formats. This
module is the one funnel: watchdog retraces, fault injections, retry
exhaustion, coordinated-checkpoint commits/aborts, elastic restarts,
collective timeouts, device OOMs, XLA compiles, and fleet straggler
detections all `emit()` here with ONE schema, land in a bounded in-memory
ring (served by the ObservabilityServer's `/events` endpoint and folded
into bench JSON), and optionally append to a JSONL file that
`tools/obs_tail.py` tails/filters/pretty-prints.

Schema (flat JSON object per line):

    required  ts: float      unix seconds
              kind: str      ^[a-z][a-z0-9_]*$ (see KINDS for the set the
                             runtime emits today)
              host: str      stable host identity (PADDLE_CURRENT_ENDPOINT,
                             else trainer-<PADDLE_TRAINER_ID>, else
                             <hostname>:<pid>)
    optional  severity: str  debug | info | warn | error (default info)
              ...            kind-specific payload keys, all JSON scalars
                             (lists/dicts allowed but keep events greppable)

`validate_event` is the schema contract tests and
`tools/check_bench_result.py` check against. Kill switch:
`PADDLE_TPU_EVENTS=0` makes every emit a no-op. `PADDLE_TPU_EVENT_LOG=path`
appends each event as one JSON line (the obs_tail input); with
`PADDLE_TPU_EVENT_LOG_MAX_MB=N` the sink rotates size-based (`path` ->
`path.1` -> ... keeping the newest `PADDLE_TPU_EVENT_LOG_KEEP` rotated
files, default 3) so a long fleet run cannot grow the file unboundedly —
`tools/obs_tail.py` reads rotated siblings transparently.
"""
from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["EventLog", "default_event_log", "emit", "recent",
           "validate_event", "KINDS", "KIND_SEVERITY", "SEVERITIES",
           "host_id"]

#: kinds the runtime emits today -> their DECLARED baseline severity
#: (what the emitter uses in the common case; some kinds escalate, e.g.
#: health_alert warn->error on halt). This table is the source of truth
#: the convention lint (analysis/conventions.py lint_event_kinds) holds
#: every `emit("<kind>", ...)` call site against, and every kind here
#: must render through tools/obs_tail.py (not drop as garbage) — the
#: pairing is pinned by tests/test_conventions.py. Not a closed set for
#: VALIDATION (any ^[a-z][a-z0-9_]*$ name validates, so downstream
#: tooling stays generic) — but a new emitter must register here.
KIND_SEVERITY = {
    "retrace": "info",            # watchdog: new jit signature, warm site
    "xla_compile": "info",        # backend compile, attributed to entry
    "fault_injected": "warn",     # an armed fault site fired
    "retry_exhausted": "error",   # a retried op failed every attempt
    "retry_recovered": "info",    # a retried op succeeded after retries
    "barrier_commit": "info",     # coordinated checkpoint committed
    "barrier_abort": "warn",      # coordinated checkpoint aborted
    "elastic_restart": "warn",    # supervisor relaunched the trainer
    "collective_timeout": "error",  # eager collective blew its deadline
    "device_oom": "error",        # eager op exhausted device memory
    "fleet_straggler": "warn",    # a host's step p50 left the fleet band
    "step_diagnosis": "info",     # step wall-time decomposition
    "profile_capture": "warn",    # a profiler capture session ended
    "tensor_health": "error",     # NaN/Inf detected (sentinel or eager)
    "health_alert": "warn",       # HealthMonitor signal (spike/...)
    "health_rollback": "warn",    # divergence response restored a ckpt
    "fleet_health": "error",      # a host's digest went non-ok
    "controller_decision": "warn",  # controller evict/readmit/rollback
    "elastic_budget_reset": "info",  # healthy window restored the budget
    "serving_admission": "info",  # request entered the decode batch
    "serving_eviction": "info",   # request left the batch (eos/length/
                                  # preempted/failed), pages freed
    "analysis_finding": "warn",   # static program auditor finding
                                  # (severity tracks the finding's own)
    "request_trace": "info",      # a serving request's lifecycle trace
                                  # completed (warn when it failed)
    "slo_breach": "warn",         # a serving SLO window left its target
                                  # (one per excursion; re-arms on
                                  # recovery)
    "serving_swap": "warn",       # weight hot-swap lifecycle (stage/
                                  # swap/reject/rollback/fail/halt)
    "serving_restart": "warn",    # wedged engine restarted; in-flight
                                  # requests requeued, pages rebuilt
    "controller_takeover": "warn",  # a controller acquired the leader
                                    # lease (bootstrap / lease_expired)
    "controller_fenced": "warn",  # stale-term actuation rejected (a
                                  # deposed leader tried to act)
    "fleet_leaderless": "warn",   # no controller renewed the lease for
                                  # over one TTL — failover cover gone
    "disagg_worker_restart": "warn",  # dead/wedged prefill worker
                                      # respawned; its work requeued
}

#: back-compat view: the registered kind names
KINDS = tuple(KIND_SEVERITY)

SEVERITIES = ("debug", "info", "warn", "error")

_KIND_RE = re.compile(r"^[a-z][a-z0-9_]*$")

_RESERVED = ("ts", "kind", "host", "severity")


def host_id() -> str:
    """Stable identity of this process for the `host` field — the same id
    the elastic membership watch uses (PADDLE_CURRENT_ENDPOINT, which
    tools/elastic_run.py pins to trainer-<rank>)."""
    ep = os.environ.get("PADDLE_CURRENT_ENDPOINT")
    if ep:
        return ep
    rank = os.environ.get("PADDLE_TRAINER_ID")
    if rank:
        return f"trainer-{rank}"
    return f"{socket.gethostname()}:{os.getpid()}"


def validate_event(rec: dict) -> dict:
    """Raise ValueError (naming every violation) unless `rec` conforms to
    the event schema; returns the record for chaining."""
    if not isinstance(rec, dict):
        raise ValueError(f"event must be a dict, got {type(rec)}")
    problems = []
    if not isinstance(rec.get("ts"), (int, float)) \
            or isinstance(rec.get("ts"), bool):
        problems.append(f"'ts' must be numeric, got {rec.get('ts')!r}")
    kind = rec.get("kind")
    if not isinstance(kind, str) or not _KIND_RE.match(kind):
        problems.append(f"'kind' must match {_KIND_RE.pattern}, "
                        f"got {kind!r}")
    if not isinstance(rec.get("host"), str) or not rec.get("host"):
        problems.append(f"'host' must be a non-empty string, "
                        f"got {rec.get('host')!r}")
    sev = rec.get("severity", "info")
    if sev not in SEVERITIES:
        problems.append(f"'severity' must be one of {SEVERITIES}, "
                        f"got {sev!r}")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        problems.append(f"payload is not JSON-serializable: {e}")
    if problems:
        raise ValueError("invalid event: " + "; ".join(problems))
    return rec


def _enabled() -> bool:
    return os.environ.get("PADDLE_TPU_EVENTS", "1").lower() not in (
        "0", "false", "off", "no")


class EventLog:
    """Bounded ring of structured events + optional JSONL file sink.

    Thread-safe; emit cost with the sink disabled is one dict build + one
    deque append under a lock (events are rare — retraces, faults,
    restarts — never per-op)."""

    def __init__(self, capacity: Optional[int] = None,
                 jsonl_path: Optional[str] = None):
        if capacity is None:
            from ..utils.envparse import env_int
            capacity = env_int("PADDLE_TPU_EVENT_BUFFER", 512)
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=max(int(capacity), 1))
        self._counts: Dict[str, int] = {}
        self._path = jsonl_path
        self._file = None
        self._file_error = False

    # -- emission ------------------------------------------------------------
    def emit(self, kind: str, severity: str = "info", **data) -> Optional[dict]:
        """Append one event; returns the record (None when disabled).
        Reserved keys (ts/kind/host/severity) cannot be overridden by
        payload kwargs."""
        if not _enabled():
            return None
        rec = {"ts": time.time(), "kind": kind, "host": host_id(),
               "severity": severity}
        for k, v in data.items():
            if k not in _RESERVED:
                rec[k] = v
        with self._lock:
            self._ring.append(rec)
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._write_line(rec)
        return rec

    def _write_line(self, rec: dict):
        """Append to the JSONL sink (lazy open; one failure disables the
        sink with a single warning — the ring keeps working). Rotates the
        file size-based when PADDLE_TPU_EVENT_LOG_MAX_MB is set."""
        if self._file_error:
            return
        path = self._path or os.environ.get("PADDLE_TPU_EVENT_LOG")
        if not path:
            return
        try:
            if self._file is None or self._file.name != path:
                if self._file is not None:
                    self._file.close()
                self._file = open(path, "a")
            self._file.write(json.dumps(rec) + "\n")
            self._file.flush()
        except Exception as e:
            self._file_error = True
            import warnings
            warnings.warn(f"event JSONL sink {path!r} failed ({e}); "
                          f"events stay in memory only")
            return
        self._maybe_rotate(path)

    def _maybe_rotate(self, path: str):
        """Size-based rotation: once the sink passes
        PADDLE_TPU_EVENT_LOG_MAX_MB, shift `path` -> `path.1` (existing
        `path.N` -> `path.N+1`, newest-first numbering) and keep only the
        newest PADDLE_TPU_EVENT_LOG_KEEP rotated files. A rotation
        failure never disables the sink — worse to lose events than to
        let the file grow."""
        from ..utils.envparse import env_float, env_int
        max_bytes = env_float("PADDLE_TPU_EVENT_LOG_MAX_MB", 0.0) * (1 << 20)
        if max_bytes <= 0:
            return
        try:
            if self._file.tell() < max_bytes:
                return
            keep = max(0, env_int("PADDLE_TPU_EVENT_LOG_KEEP", 3))
            self._file.close()
            self._file = None  # lazy reopen on the next emit
            oldest = f"{path}.{keep}"
            if keep == 0:
                os.remove(path)
                return
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(keep - 1, 0, -1):
                if os.path.exists(f"{path}.{i}"):
                    os.replace(f"{path}.{i}", f"{path}.{i + 1}")
            os.replace(path, f"{path}.1")
        except Exception:
            pass

    # -- reading -------------------------------------------------------------
    def recent(self, n: int = 100, kind: Optional[str] = None,
               min_severity: Optional[str] = None) -> List[dict]:
        """Newest-last list of up to `n` events, optionally filtered."""
        with self._lock:
            events = list(self._ring)
        if kind:
            events = [e for e in events if e.get("kind") == kind]
        if min_severity:
            floor = SEVERITIES.index(min_severity)
            events = [e for e in events
                      if SEVERITIES.index(e.get("severity", "info")) >= floor]
        return events[-max(int(n), 0):]

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._counts.clear()

    def close(self):
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except Exception:
                    pass
                self._file = None


_default = EventLog()


def default_event_log() -> EventLog:
    return _default


def emit(kind: str, severity: str = "info", **data) -> Optional[dict]:
    """Module-level shorthand: `events.emit("retrace", site=..., ...)`."""
    return _default.emit(kind, severity=severity, **data)


def recent(n: int = 100, kind: Optional[str] = None) -> List[dict]:
    return _default.recent(n, kind=kind)
