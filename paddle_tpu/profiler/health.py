"""Training-health numerics plane: in-graph NaN/Inf sentinels, per-layer
gradient telemetry, first-NaN attribution, divergence auto-response.

The reference framework treats numerics as a first-class observable:
`FLAGS_check_nan_inf` hooks every op post-execution with
`CheckOpHasNanOrInf` (`framework/details/nan_inf_utils.h:29`), naming the
op and tensor that produced the first bad value. This module is the
TPU-native port of that idea, with three detection tiers that respect how
production steps actually run (ONE compiled XLA program, where a per-op
host check is impossible and `jax_debug_nans` is inert):

1.  **In-graph sentinel** — :class:`HealthProbe` folds a small packed
    stats vector into the compiled ``TrainStep``: loss value, an
    any-nonfinite flag, the global grad norm, per-layer-group grad norms
    (bucketed parameter-tree paths, bounded cardinality), and the
    update/param ratio. All reductions run on-device in the same XLA
    program; the host fetches ONE tiny vector per step (or every N steps,
    ``PADDLE_TPU_HEALTH_INTERVAL``) — no per-tensor syncs.

2.  **Eager first-NaN attribution** — under ``FLAGS_check_nan_inf`` the
    eager dispatch post-checks every op output (the reference's
    ``CheckOpHasNanOrInfInDygraph`` analogue) and, on the first bad
    value, emits a ``tensor_health`` event naming the op, the layer path
    (a thread-local layer stack armed only while checking), the
    shape/dtype, and the bad-value kind. Compiled steps get the same
    attribution without permanently paying eager cost: when the sentinel
    trips, :func:`eager_replay` re-runs the last batch's forward+loss
    eagerly ONCE with the checks armed.

3.  **Trend detection + auto-response** — :class:`HealthMonitor` (a hapi
    callback, sibling of ``ThroughputMonitor``) tracks loss
    spikes/divergence (EWMA + z-score), grad-norm explosion/vanishing,
    and stagnation; emits ``health_*`` metric families and structured
    events into the observability plane, and on confirmed divergence runs
    the configured response (``PADDLE_TPU_HEALTH_ACTION``): ``warn`` |
    ``halt`` | ``rollback`` (restore the last valid checkpoint through
    the existing ``CheckpointManager`` machinery, bit-identically) |
    ``fleet`` (pin the ``diverged`` status into the fleet digest and
    WAIT — the supervisor-side fleet controller escalates one host's
    divergence to a coordinated fleet-wide rollback relaunch).

Opt-in: ``PADDLE_TPU_HEALTH=1`` or ``FLAGS_check_nan_inf`` arms the
sentinel on every subsequently-built ``TrainStep``; the eager per-op
check follows ``FLAGS_check_nan_inf`` alone (it crashes on the first bad
op, reference semantics). ``PADDLE_TPU_DEBUG_NANS=1`` /
``FLAGS_debug_nans`` is the explicit escape hatch to jax's own
``jax_debug_nans`` (see framework/flags.py).
"""
from __future__ import annotations

import math
import os
import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..framework import flags as _flags_mod
from . import events as _events_mod
from . import metrics as _metrics_mod

__all__ = [
    "HealthProbe", "HealthMonitor", "enabled", "interval", "record_step_stats",
    "last_stats", "last_status", "snapshot", "eager_replay", "note_bad_tensor",
    "index_model", "reset", "HEALTH_EVENT_KINDS",
]

#: event kinds this plane emits (subset of events.KINDS)
HEALTH_EVENT_KINDS = ("tensor_health", "health_alert", "health_rollback")

_REG = _metrics_mod.default_registry()
_M_LOSS = _REG.gauge(
    "health_loss",
    "newest loss value the health sentinel fetched (finite values only)")
_M_GRAD_NORM = _REG.gauge(
    "health_grad_norm",
    "newest global gradient L2 norm from the in-graph sentinel (finite "
    "values only)")
_M_UPDATE_RATIO = _REG.gauge(
    "health_update_ratio",
    "newest parameter update/param L2-norm ratio from the sentinel "
    "(finite values only)")
_M_LAYER_GRAD = _REG.gauge(
    "health_layer_grad_norm",
    "per-layer-group gradient L2 norm from the sentinel, by group "
    "(bucketed parameter-tree path, bounded cardinality)")
_M_NONFINITE = _REG.counter(
    "health_nonfinite_total",
    "nonfinite detections by src (sentinel: the in-graph probe tripped; "
    "eager: the per-op dispatch post-check fired)")
_M_ALERTS = _REG.counter(
    "health_alerts_total",
    "HealthMonitor alerts by signal (nonfinite, loss_spike, "
    "grad_explosion, grad_vanishing, stagnation)")
_M_ROLLBACK = _REG.counter(
    "health_rollback_total",
    "divergence auto-responses that restored the last valid checkpoint")


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------
def enabled() -> bool:
    """True when the in-graph sentinel should be folded into compiled
    steps: PADDLE_TPU_HEALTH=1, or the reference flag FLAGS_check_nan_inf
    (which also arms the eager per-op check)."""
    if os.environ.get("PADDLE_TPU_HEALTH", "").lower() in (
            "1", "true", "yes", "on"):
        return True
    try:
        return bool(_flags_mod.flag("FLAGS_check_nan_inf"))
    except Exception:
        return False


def interval() -> int:
    """Sentinel fetch cadence in steps (the vector is computed in-graph
    every step either way; this bounds the device->host transfers and the
    detection latency)."""
    from ..utils.envparse import env_int
    return max(1, env_int("PADDLE_TPU_HEALTH_INTERVAL", 1))


def action() -> str:
    """The configured divergence response: warn | halt | rollback | fleet."""
    a = os.environ.get("PADDLE_TPU_HEALTH_ACTION", "warn").lower()
    return a if a in ("warn", "halt", "rollback", "fleet") else "warn"


def max_groups() -> int:
    from ..utils.envparse import env_int
    return max(1, env_int("PADDLE_TPU_HEALTH_GROUPS", 32))


# ---------------------------------------------------------------------------
# tier 1: in-graph sentinel
# ---------------------------------------------------------------------------
def _group_name(param_name: str) -> str:
    """Bucket a dotted parameter path into a layer group: drop the leaf
    (weight/bias/...), keep the first two components of what remains —
    'blocks.3.attn.qkv.weight' -> 'blocks.3', 'fc2.bias' -> 'fc2'."""
    parts = param_name.split(".")[:-1]
    return ".".join(parts[:2]) if parts else "(root)"


class HealthProbe:
    """Builds the packed on-device stats vector for one parameter tree.

    The vector layout is fixed at construction (group names are derived
    from the FLAT param dict the TrainStep already holds), so
    :meth:`stats_vec` is pure and traceable and :meth:`decode` needs no
    device round-trips beyond the one fetch of the vector itself.

    Layout: ``[loss, nonfinite_flag, grad_sq, update_sq, param_sq,
    group_0_grad_sq, ..., group_{G-1}_grad_sq,
    group_0_param_bad, ..., group_{G-1}_param_bad]`` — all float32.

    The per-group PARAM nonfinite flags are what make first-bad-layer
    attribution precise: once a loss goes NaN, backprop poisons every
    layer's gradients in the same step, but the incoming (pre-update)
    params are only bad in the group that actually went bad first.
    """

    N_FIXED = 5

    def __init__(self, params: Dict[str, object],
                 max_groups_: Optional[int] = None):
        cap = max_groups_ if max_groups_ is not None else max_groups()
        raw: Dict[str, List[str]] = {}
        for name in params:
            raw.setdefault(_group_name(name), []).append(name)
        names = sorted(raw)
        self._group_of: Dict[str, int] = {}
        if len(names) > cap:
            # bounded cardinality: hash-bucket the tree paths so the
            # vector (and the gauge label set) never grows with model depth
            self.group_names = [f"bucket{i:02d}" for i in range(cap)]
            for gname, members in raw.items():
                idx = zlib.crc32(gname.encode()) % cap
                for m in members:
                    self._group_of[m] = idx
        else:
            self.group_names = names
            for i, gname in enumerate(names):
                for m in raw[gname]:
                    self._group_of[m] = i

    def stats_vec(self, loss, grads, params, new_params):
        """Traced: the packed float32 stats vector (see class docstring).
        Every reduction is tiny next to the step's matmuls and fuses into
        the same XLA program."""
        f32 = jnp.float32
        zero = jnp.zeros((), f32)
        group_sq = [zero] * len(self.group_names)
        grad_sq = zero
        bad = jnp.zeros((), jnp.bool_)
        for name, g in grads.items():
            if not jnp.issubdtype(g.dtype, jnp.floating):
                continue
            s = jnp.sum(jnp.square(g.astype(f32)))
            grad_sq = grad_sq + s
            i = self._group_of.get(name)
            if i is not None:
                group_sq[i] = group_sq[i] + s
            bad = bad | ~jnp.all(jnp.isfinite(g))
        upd_sq = zero
        par_sq = zero
        group_bad = [jnp.zeros((), jnp.bool_)] * len(self.group_names)
        for name, p in params.items():
            q = new_params.get(name) if hasattr(new_params, "get") else None
            if q is None or not jnp.issubdtype(
                    jnp.asarray(p).dtype, jnp.floating):
                continue
            d = q.astype(f32) - p.astype(f32)
            upd_sq = upd_sq + jnp.sum(jnp.square(d))
            par_sq = par_sq + jnp.sum(jnp.square(p.astype(f32)))
            i = self._group_of.get(name)
            if i is not None:
                group_bad[i] = group_bad[i] | ~jnp.all(jnp.isfinite(p))
                bad = bad | group_bad[i]
        loss32 = jnp.asarray(loss, f32).reshape(())
        bad = bad | ~jnp.isfinite(loss32)
        return jnp.stack([loss32, bad.astype(f32), grad_sq, upd_sq, par_sq]
                         + group_sq + [b.astype(f32) for b in group_bad])

    def decode(self, vec) -> dict:
        """Host side: one fetched vector -> a stats dict. The fetch
        (np.asarray) is the single device->host transfer of the tier."""
        v = np.asarray(vec, dtype=np.float64)
        nonfinite = bool(v[1] > 0) or not bool(np.all(np.isfinite(v)))
        n_groups = len(self.group_names)
        with np.errstate(invalid="ignore"):
            grad_norm = float(np.sqrt(v[2]))
            upd = float(np.sqrt(v[3]))
            par = float(np.sqrt(v[4]))
            groups = {name: float(np.sqrt(v[self.N_FIXED + i]))
                      for i, name in enumerate(self.group_names)}
        bad_params = [name for i, name in enumerate(self.group_names)
                      if v[self.N_FIXED + n_groups + i] > 0]
        return {
            "loss": float(v[0]),
            "nonfinite": nonfinite,
            "grad_norm": grad_norm,
            "param_norm": par,
            "update_ratio": (upd / par) if par > 0 else upd,
            "group_grad_norms": groups,
            # groups whose incoming (pre-update) params held NaN/Inf —
            # the first-bad-layer attribution (see class docstring)
            "bad_param_groups": bad_params,
        }


# ---------------------------------------------------------------------------
# module state: last sentinel stats / status / alerts (the /snapshot and
# fleet-digest surface)
# ---------------------------------------------------------------------------
_state_lock = threading.Lock()
_last_stats: Optional[dict] = None
_status: Optional[str] = None          # ok | warn | diverged
_alerts: "deque[dict]" = deque(maxlen=32)
_rollback_count = 0
_trip_active = False                   # sentinel currently tripped
_last_attribution: Optional[dict] = None


def _f(x) -> Optional[float]:
    """Finite float or None — keeps NaN/Inf out of gauges, JSON payloads
    and fleet digests."""
    try:
        x = float(x)
    except (TypeError, ValueError):
        return None
    return x if math.isfinite(x) else None


def record_step_stats(stats: dict, step: int,
                      source: str = "sentinel") -> dict:
    """Fold one decoded sentinel fetch into the health plane: gauges,
    last-stats snapshot, status, and (on a nonfinite flag) the
    ``tensor_health`` trip event. Returns the stored record. Never
    raises — health telemetry must not take down training."""
    global _last_stats, _status, _trip_active
    rec = dict(stats)
    rec["step"] = int(step)
    rec["ts"] = time.time()
    nonfinite = bool(rec.get("nonfinite"))
    try:
        if _metrics_mod.enabled():
            for gauge, key in ((_M_LOSS, "loss"),
                               (_M_GRAD_NORM, "grad_norm"),
                               (_M_UPDATE_RATIO, "update_ratio")):
                val = _f(rec.get(key))
                if val is not None:
                    gauge.set(val)
            for gname, gv in (rec.get("group_grad_norms") or {}).items():
                val = _f(gv)
                if val is not None:
                    _M_LAYER_GRAD.set(val, group=gname)
    except Exception:
        pass
    with _state_lock:
        _last_stats = rec
        tripped_now = nonfinite and not _trip_active
        _trip_active = nonfinite
        _status = "diverged" if nonfinite else (
            "ok" if _status != "warn" else _status)
    if tripped_now:
        # name the origin: groups whose pre-update PARAMS were bad (the
        # layer that actually went bad first), else the groups whose grad
        # norms came back nonfinite (loss/activation-level blowup — once
        # the loss is NaN, backprop poisons every group the same step)
        bad_groups = list(rec.get("bad_param_groups") or [])
        if not bad_groups:
            bad_groups = sorted(
                g for g, v in (rec.get("group_grad_norms") or {}).items()
                if _f(v) is None)
        try:
            if _metrics_mod.enabled():
                _M_NONFINITE.inc(src=source)
            _events_mod.emit(
                "tensor_health", severity="error", src=source,
                step=int(step), loss=_f(rec.get("loss")),
                grad_norm=_f(rec.get("grad_norm")),
                bad_groups=bad_groups)
        except Exception:
            pass
    return rec


def last_stats() -> Optional[dict]:
    with _state_lock:
        return dict(_last_stats) if _last_stats else None


def last_status() -> Optional[str]:
    with _state_lock:
        return _status


def set_status(status: str):
    global _status
    with _state_lock:
        _status = status


def tripped() -> bool:
    """True while the newest sentinel fetch held NaN/Inf. The
    FaultTolerantCheckpoint consults this to SKIP saves of known-bad
    state — a CRC-valid checkpoint of NaN weights would poison the very
    rollback path that is supposed to recover from it."""
    with _state_lock:
        return _trip_active


def clear_trip():
    """Re-arm the sentinel trip (after a rollback restored good state)."""
    global _trip_active
    with _state_lock:
        _trip_active = False


def note_alert(rec: dict):
    with _state_lock:
        _alerts.append(rec)


def note_rollback():
    global _rollback_count
    with _state_lock:
        _rollback_count += 1


def _json_safe(obj):
    """Recursively replace nonfinite floats with None — a tripped
    sentinel's raw stats hold NaN, and NaN in /snapshot would break
    strict-JSON consumers (jq, browsers)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def snapshot() -> dict:
    """The /snapshot ``health`` section."""
    with _state_lock:
        return {
            "enabled": enabled(),
            "eager_check": bool(_ATTRIBUTION_ARMED),
            "interval": interval(),
            "action": action(),
            "status": _status,
            "tripped": _trip_active,
            "last": _json_safe(dict(_last_stats)) if _last_stats else None,
            "last_attribution": (dict(_last_attribution)
                                 if _last_attribution else None),
            "alerts_tail": [_json_safe(dict(a))
                            for a in list(_alerts)[-10:]],
            "rollbacks": _rollback_count,
        }


def reset():
    """Test hook: clear all module state (metrics families stay)."""
    global _last_stats, _status, _rollback_count, _trip_active
    global _last_attribution
    with _state_lock:
        _last_stats = None
        _status = None
        _rollback_count = 0
        _trip_active = False
        _last_attribution = None
        _alerts.clear()


# ---------------------------------------------------------------------------
# tier 2: eager first-NaN attribution (layer stack + dispatch hook + replay)
# ---------------------------------------------------------------------------
# Fast gate read directly by nn.layer.Layer.__call__ (one module-attr test
# per layer call while armed; zero extra work otherwise). Armed while
# FLAGS_check_nan_inf is on, or for the duration of an eager_replay.
_ATTRIBUTION_ARMED = False
_tls = threading.local()

# id(layer) -> dotted path, for every model registered via index_model
_layer_index: Dict[int, str] = {}


def set_eager_check(on: bool):
    """Called by framework.flags when FLAGS_check_nan_inf changes: arms
    the layer-path stack the dispatch post-check attributes against."""
    global _ATTRIBUTION_ARMED
    _ATTRIBUTION_ARMED = bool(on)


def push_layer(layer):
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(layer)


def pop_layer():
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


def index_model(root) -> Dict[int, str]:
    """Map every sublayer of `root` to its dotted path so attribution can
    name real parameter-tree locations instead of class names."""
    idx = {id(root): "(root)"}
    try:
        for name, sub in root.named_sublayers(include_self=False):
            idx[id(sub)] = name
    except Exception:
        pass
    _layer_index.update(idx)
    return idx


def current_layer_path() -> Optional[str]:
    """Innermost indexed layer on this thread's call stack; falls back to
    the class-name chain when no model was indexed."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return None
    for layer in reversed(stack):
        path = _layer_index.get(id(layer))
        if path is not None:
            return path
    return "/".join(type(l).__name__ for l in stack)


def note_bad_tensor(op: str, output_index: int, shape, dtype: str,
                    kind: str) -> dict:
    """Called by the dispatch post-check on the FIRST bad op output: emit
    the `tensor_health` attribution event naming op + layer path +
    shape/dtype + bad-value kind. Returns the record."""
    global _last_attribution
    rec = {
        "src": "eager",
        "op": op,
        "layer": current_layer_path(),
        "output_index": int(output_index),
        "shape": list(shape),
        "dtype": str(dtype),
        "bad_kind": kind,
    }
    with _state_lock:
        _last_attribution = rec
    try:
        if _metrics_mod.enabled():
            _M_NONFINITE.inc(src="eager")
        _events_mod.emit("tensor_health", severity="error", **rec)
    except Exception:
        pass
    return rec


def eager_replay(layer, loss_fn: Callable, arrs) -> Optional[dict]:
    """One-shot compiled-step attribution: re-run the last batch's
    forward + loss EAGERLY with the per-op NaN check armed. The dispatch
    post-check raises on (and attributes) the first bad op output; the
    exception is swallowed here — this is diagnosis, not control flow.
    Returns the attribution record, or None if the eager pass stayed
    clean (e.g. only the optimizer update was bad)."""
    global _last_attribution
    from ..framework import tape as tape_mod
    from ..framework.tensor import Tensor
    flag = _flags_mod._REGISTRY["FLAGS_check_nan_inf"]
    prev_flag, prev_armed = flag.value, _ATTRIBUTION_ARMED
    index_model(layer)
    with _state_lock:
        _last_attribution = None
    flag.value = True
    set_eager_check(True)
    try:
        inputs = [Tensor(a) for a in arrs[:-1]]
        label = Tensor(arrs[-1])
        with tape_mod.no_grad():
            out = layer(*inputs)
            loss_fn(out, label)
    except FloatingPointError:
        pass  # note_bad_tensor already recorded the attribution
    except Exception:
        pass  # replay is best-effort; never take down the train loop
    finally:
        flag.value = prev_flag
        set_eager_check(prev_armed)
    with _state_lock:
        return dict(_last_attribution) if _last_attribution else None


# arm the eager-attribution stack if the flag was set via environment
# before this module loaded (flags.py forwards later runtime changes)
try:
    set_eager_check(bool(_flags_mod.flag("FLAGS_check_nan_inf")))
except Exception:
    pass


# ---------------------------------------------------------------------------
# tier 3: trend detection + auto-response
# ---------------------------------------------------------------------------
def _blob_finite(blob) -> bool:
    """True when every floating network param in a checkpoint blob is
    finite (one host-side pass; rollback-path only, never per step)."""
    try:
        net = blob.get("network") if isinstance(blob, dict) else None
        if not isinstance(net, dict):
            return True  # unknown shape: nothing to judge, accept
        for v in net.values():
            a = np.asarray(getattr(v, "data", v))
            if a.dtype.kind == "f":
                pass
            elif "float" in str(a.dtype):  # bfloat16/float8 via ml_dtypes
                a = a.astype(np.float32)
            else:
                continue
            if not np.all(np.isfinite(a)):
                return False
        return True
    except Exception:
        return True


class HealthMonitor:
    """hapi callback (duck-typed like ThroughputMonitor): loss-spike /
    divergence / grad-explosion / vanishing / stagnation detection over
    the sentinel stats (or, without a sentinel, the per-batch loss logs),
    with the configured auto-response on confirmed divergence.

    Usage::

        model.fit(..., callbacks=[
            FaultTolerantCheckpoint(dirname, save_freq_steps=50),
            HealthMonitor(action="rollback", checkpoint=dirname)])

    Detection:
      * nonfinite loss/grads (sentinel trip or a NaN/Inf loss log) —
        immediately CONFIRMED divergence;
      * loss spike: EWMA mean/variance z-score above ``z_threshold`` for
        ``confirm_steps`` consecutive steps — CONFIRMED divergence;
      * grad explosion (norm > ``explode_factor`` x its EWMA), vanishing
        (norm < ``vanish_threshold`` for ``vanish_steps``), stagnation
        (relative EWMA loss change < ``stagnation_rel`` over
        ``stagnation_steps``) — warn-level alerts only.

    Response (``action``, default from ``PADDLE_TPU_HEALTH_ACTION``):
      * ``warn``     — the ``health_alert`` event only;
      * ``halt``     — set ``model.stop_training`` (fit stops at the next
        batch boundary);
      * ``rollback`` — restore the last VALID checkpoint (model +
        optimizer + compiled-step slots + RNG) through `checkpoint` (a
        ``FaultTolerantCheckpoint`` callback, a ``CheckpointManager``, or
        a directory path), count ``health_rollback_total``, and keep
        training. The restore is bit-identical to a fresh
        ``fit(resume=)`` from the same file. ``cooldown_steps`` suppresses
        re-detection while the EWMA re-converges; after ``max_rollbacks``
        the monitor degrades to halt (a model that keeps diverging from
        the same checkpoint will not be saved by another restore);
      * ``fleet``    — defer to the supervisor-side fleet controller: pin
        ``diverged`` into this host's fleet digest and keep running until
        the controller's coordinated fleet-wide rollback relaunches the
        process (every host then resumes the same last numerically-valid
        committed step under ``PADDLE_TPU_RESUME_VALID_ONLY``). The local
        monitor takes no action of its own — a local rollback would race
        the fleet-wide one.
    """

    def __init__(self, action: Optional[str] = None, window: int = 50,
                 z_threshold: float = 6.0, confirm_steps: int = 3,
                 explode_factor: float = 1000.0,
                 vanish_threshold: float = 1e-10, vanish_steps: int = 20,
                 stagnation_steps: int = 0, stagnation_rel: float = 1e-4,
                 checkpoint=None, cooldown_steps: int = 50,
                 max_rollbacks: int = 3):
        self.action = (action or globals()["action"]()).lower()
        if self.action not in ("warn", "halt", "rollback", "fleet"):
            raise ValueError(f"unknown health action {self.action!r} "
                             f"(expected warn | halt | rollback | fleet)")
        self.window = max(int(window), 2)
        self.z_threshold = float(z_threshold)
        self.confirm_steps = max(int(confirm_steps), 1)
        self.explode_factor = float(explode_factor)
        self.vanish_threshold = float(vanish_threshold)
        self.vanish_steps = max(int(vanish_steps), 1)
        self.stagnation_steps = int(stagnation_steps)  # 0 = disabled
        self.stagnation_rel = float(stagnation_rel)
        self.checkpoint = checkpoint
        self.cooldown_steps = max(int(cooldown_steps), 0)
        self.max_rollbacks = max(int(max_rollbacks), 0)
        self.model = None
        self.params = {}
        self.alerts: List[dict] = []
        self.rollbacks = 0
        self._reset_detectors()
        self._global_step = 0
        self._last_seen_stats_ts = None
        self._cooldown_until = -1

    # -- hapi protocol -------------------------------------------------------
    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model
        net = getattr(model, "network", model)
        try:
            index_model(net)
        except Exception:
            pass

    def _reset_detectors(self):
        self._ewma_loss = None
        self._ewma_var = 0.0
        self._ewma_grad = None
        self._n_obs = 0  # losses observed since the last (re)baseline
        self._spike_streak = 0
        self._vanish_streak = 0
        self._stagnation_anchor = None  # (step, ewma_loss)

    def on_train_begin(self, logs=None):
        self._global_step = 0
        self._reset_detectors()

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        stats = last_stats()
        fresh = (stats is not None
                 and stats.get("ts") != self._last_seen_stats_ts)
        if fresh:
            self._last_seen_stats_ts = stats.get("ts")
        loss = None
        grad_norm = None
        nonfinite = False
        if fresh:
            loss = stats.get("loss")
            grad_norm = _f(stats.get("grad_norm"))
            nonfinite = bool(stats.get("nonfinite"))
        elif isinstance(logs, dict) and logs.get("loss") is not None:
            try:
                loss = float(np.asarray(logs["loss"]).ravel()[0])
            except Exception:
                loss = None
        self.observe(loss=loss, grad_norm=grad_norm, nonfinite=nonfinite,
                     step=self._global_step)

    # unused hooks (hapi CallbackList calls them all)
    def on_train_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass

    # -- detection -----------------------------------------------------------
    def observe(self, loss: Optional[float] = None,
                grad_norm: Optional[float] = None,
                nonfinite: bool = False, step: Optional[int] = None):
        """Feed one step's signals (also the manual-loop entry point).
        Runs the detectors and, on confirmed divergence, the response."""
        if step is None:
            self._global_step += 1
            step = self._global_step
        else:
            self._global_step = int(step)
        if step <= self._cooldown_until:
            return
        warned = False
        if loss is not None:
            try:
                loss = float(loss)
            except (TypeError, ValueError):
                loss = None
            else:
                if not math.isfinite(loss):
                    nonfinite = True
        if nonfinite:
            self._alert("nonfinite", step, severity="error",
                        loss=_f(loss), grad_norm=_f(grad_norm))
            self._respond("nonfinite", step)
            self._after_response(step)
            return
        if loss is not None and math.isfinite(loss):
            warned |= self._observe_loss(float(loss), step)
        if grad_norm is not None and math.isfinite(grad_norm):
            warned |= self._observe_grad(float(grad_norm), step)
        if not warned and not tripped() and \
                last_status() in ("warn", "diverged"):
            # a clean step re-arms the fleet's transition detector; a
            # logs-only monitor (no sentinel) would otherwise report
            # 'diverged' forever after one confirmed spike. While the
            # sentinel IS tripped it stays authoritative.
            if self.action == "fleet" and last_status() == "diverged":
                # pinned: the fleet controller owns the response, and its
                # poll cadence must not race a one-step excursion that a
                # clean successor would otherwise flap back to "ok" before
                # the digest publishes — only the controller's rollback
                # relaunch (a fresh process) clears a fleet-mode diverged
                return
            set_status("ok")

    def _observe_loss(self, loss: float, step: int) -> bool:
        alpha = 2.0 / (self.window + 1.0)
        warned = False
        self._n_obs += 1
        if self._ewma_loss is None:
            self._ewma_loss = loss
            self._ewma_var = 0.0
        else:
            dev = loss - self._ewma_loss
            # std floor is RELATIVE to the loss level (plus an absolute
            # epsilon): a near-constant warmup loss would otherwise give
            # std ~ 1e-6 and any normal noise a five-digit z-score. The
            # warmup gate counts losses OBSERVED since (re)baseline, not
            # the caller's absolute step number — manual loops hand in
            # mid-run counters
            std = max(math.sqrt(max(self._ewma_var, 0.0)),
                      1e-3 * abs(self._ewma_loss), 1e-9)
            z = dev / std
            if z > self.z_threshold and self._n_obs > self.window // 2:
                self._spike_streak += 1
                if self._spike_streak >= self.confirm_steps:
                    self._alert("loss_spike", step, severity="error",
                                loss=loss, z=round(z, 2),
                                ewma=round(self._ewma_loss, 6))
                    self._respond("loss_spike", step)
                    self._after_response(step)
                    return True
                warned = True
                self._alert("loss_spike_suspect", step, severity="warn",
                            loss=loss, z=round(z, 2),
                            streak=self._spike_streak)
                # do NOT fold a suspected outlier into the EWMA baseline:
                # a diverging loss would inflate the variance enough to
                # hide its own successors from the z-test and the streak
                # would never confirm
            else:
                self._spike_streak = 0
                self._ewma_var = (1 - alpha) * (
                    self._ewma_var + alpha * dev * dev)
                self._ewma_loss += alpha * dev
        # stagnation: relative EWMA movement below threshold over a window
        if self.stagnation_steps > 0:
            if self._stagnation_anchor is None:
                self._stagnation_anchor = (step, self._ewma_loss)
            else:
                a_step, a_loss = self._stagnation_anchor
                if step - a_step >= self.stagnation_steps:
                    denom = max(abs(a_loss), 1e-12)
                    if abs(self._ewma_loss - a_loss) / denom < \
                            self.stagnation_rel:
                        warned = True
                        self._alert("stagnation", step, severity="warn",
                                    ewma=round(self._ewma_loss, 6),
                                    over_steps=step - a_step)
                    self._stagnation_anchor = (step, self._ewma_loss)
        return warned

    def _observe_grad(self, norm: float, step: int) -> bool:
        warned = False
        if self._ewma_grad is not None and self._ewma_grad > 0 and \
                norm > self.explode_factor * self._ewma_grad:
            warned = True
            self._alert("grad_explosion", step, severity="warn",
                        grad_norm=norm,
                        ewma=round(self._ewma_grad, 9))
        if norm < self.vanish_threshold:
            self._vanish_streak += 1
            if self._vanish_streak == self.vanish_steps:
                warned = True
                self._alert("grad_vanishing", step, severity="warn",
                            grad_norm=norm, streak=self._vanish_streak)
        else:
            self._vanish_streak = 0
        alpha = 2.0 / (self.window + 1.0)
        self._ewma_grad = norm if self._ewma_grad is None else \
            (1 - alpha) * self._ewma_grad + alpha * norm
        return warned

    def _alert(self, signal: str, step: int, severity: str = "warn",
               **payload):
        rec = {"signal": signal, "step": int(step), "severity": severity}
        rec.update(payload)
        self.alerts.append(rec)
        note_alert(rec)
        if severity == "error":
            set_status("diverged")
        elif last_status() != "diverged":
            set_status("warn")
        try:
            if _metrics_mod.enabled():
                _M_ALERTS.inc(signal=signal)
            _events_mod.emit("health_alert", severity=severity, **rec)
        except Exception:
            pass

    def _after_response(self, step: int):
        """Re-baseline after ANY confirmed response: with action=warn a
        loss that legitimately shifted to a higher plateau would
        otherwise re-confirm against the frozen EWMA and emit one
        severity=error alert per step for the rest of the run. The
        detectors re-learn from the post-response level and the cooldown
        window suppresses re-detection meanwhile (rollback sets its own
        cooldown too — max keeps the longer one)."""
        self._reset_detectors()
        self._cooldown_until = max(self._cooldown_until,
                                   step + self.cooldown_steps)

    # -- response ------------------------------------------------------------
    def _respond(self, reason: str, step: int):
        if self.action == "halt":
            self._halt(reason, step)
        elif self.action == "rollback":
            self._rollback(reason, step)
        # warn: the alert event above is the whole response
        # fleet: the alert set status=diverged; the digest carries it to
        # the supervisor-side controller, whose coordinated rollback
        # relaunches this process — nothing to do locally but keep
        # reporting (observe() pins the status until that relaunch)

    def _halt(self, reason: str, step: int):
        if self.model is not None:
            try:
                self.model.stop_training = True
            except Exception:
                pass
        _events_mod.emit("health_alert", severity="error", signal="halt",
                         reason=reason, step=int(step))

    def _resolve_manager(self):
        ckpt = self.checkpoint
        if ckpt is None:
            return None
        from ..distributed.checkpoint import CheckpointManager, open_manager
        if isinstance(ckpt, CheckpointManager):
            return ckpt
        if hasattr(ckpt, "manager"):  # FaultTolerantCheckpoint callback
            return ckpt.manager
        return open_manager(str(ckpt))

    def _load_numerically_valid(self, mgr, step: int):
        """(blob, ckpt_step) of the newest checkpoint whose NETWORK params
        are all finite, walking back past newer files that captured
        already-poisoned state (detection lags the first bad step by up to
        one sentinel interval, so a save can legally race it)."""
        found = mgr.load_latest()
        if found is None:
            return None
        blob, ckpt_step = found
        if _blob_finite(blob):
            return blob, ckpt_step
        self._alert("rollback_skip_nonfinite", step, severity="warn",
                    skipped_step=int(ckpt_step))
        try:
            older = sorted((s for s in mgr.steps() if s < ckpt_step),
                           reverse=True)
        except Exception:
            return None
        from ..distributed.checkpoint import load as _load_ckpt
        for s in older:
            try:
                path = mgr.path_for(s)
                if os.path.isdir(path):
                    # sharded/chunked layout: a step is a DIRECTORY of
                    # chunk files + manifests, not one CRC'd blob
                    from ..distributed.sharded_checkpoint import load_step
                    blob2 = load_step(path, mesh=getattr(mgr, "mesh", None))
                else:
                    blob2 = _load_ckpt(path)
            except Exception:
                continue
            if _blob_finite(blob2):
                return blob2, s
            self._alert("rollback_skip_nonfinite", step, severity="warn",
                        skipped_step=int(s))
        return None

    def _rollback(self, reason: str, step: int):
        """Restore the last numerically-valid checkpoint into the live
        model — exactly what a fresh fit(resume=) would load — and keep
        training. Degrades to halt when no checkpoint is reachable or the
        rollback budget is spent."""
        if self.max_rollbacks and self.rollbacks >= self.max_rollbacks:
            self._alert("rollback_budget_exhausted", step, severity="error",
                        rollbacks=self.rollbacks)
            self._halt(reason, step)
            return
        try:
            mgr = self._resolve_manager()
            found = self._load_numerically_valid(mgr, step) \
                if mgr is not None else None
        except Exception as e:
            found = None
            self._alert("rollback_failed", step, severity="error",
                        error=f"{type(e).__name__}: {e}")
        if found is None:
            self._alert("rollback_unavailable", step, severity="error",
                        reason=reason)
            self._halt(reason, step)
            return
        blob, ckpt_step = found
        m = self.model
        if m is None or not isinstance(blob, dict) or "network" not in blob:
            # manual-loop monitor with no set_model(), or a blob that is
            # not a FaultTolerantCheckpoint capture: nothing to restore
            # INTO — degrade to halt instead of raising out of observe()
            # (the health plane must never take down training)
            self._alert("rollback_failed", step, severity="error",
                        error="no model attached" if m is None
                        else "checkpoint blob has no 'network' state")
            self._halt(reason, step)
            return
        try:
            m.network.set_state_dict(blob["network"])
            if blob.get("optimizer") is not None and \
                    getattr(m, "_optimizer", None) is not None:
                m._optimizer.set_state_dict(blob["optimizer"])
            # the compiled step is rebuilt from the restored network on
            # the next batch, with its slot state applied then (same path
            # as Model._restore_for_resume)
            m._pending_ts_state = blob.get("train_step")
            m._train_step = None
            if blob.get("rng") is not None:
                from ..framework.random import set_rng_state
                set_rng_state(np.asarray(blob["rng"]))
        except Exception as e:
            self._alert("rollback_failed", step, severity="error",
                        error=f"{type(e).__name__}: {e}")
            self._halt(reason, step)
            return
        self.rollbacks += 1
        note_rollback()
        clear_trip()
        set_status("ok")
        self._reset_detectors()
        self._cooldown_until = step + self.cooldown_steps
        try:
            if _metrics_mod.enabled():
                _M_ROLLBACK.inc()
            _events_mod.emit("health_rollback", severity="warn",
                             reason=reason, step=int(step),
                             restored_step=int(ckpt_step),
                             rollbacks=self.rollbacks)
        except Exception:
            pass
