"""Step-level training monitor: windowed throughput / data-wait / MFU /
retrace reporting as JSONL.

`ThroughputMonitor` is a hapi-compatible callback (duck-typed against
`hapi.callbacks.Callback` so this module stays import-cycle-free) that
combines the `timer.Benchmark` ips machinery with cost-model FLOPs and the
retrace watchdog into ONE record per step window:

    {"ts": 1722700000.0, "step": 40, "window_steps": 20,
     "step_time_ms": 12.5, "steps_per_sec": 80.0, "ips": 10240.0,
     "samples": 2560, "data_wait_frac": 0.03,
     "flops_per_step_est": 1.2e12, "mfu_est": 0.31, "retraces": 0}

The same record shape is produced by `bench.py` for its timed runs and
folded into the BENCH JSON (`observability.step_records`), so the perf
trajectory carries per-window observability from this PR on.
`validate_step_record` is the schema contract tests and tools check against.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, List, Optional

from . import metrics as metrics_mod
from . import server as server_mod
from .timer import benchmark
from .watchdog import get_watchdog

__all__ = ["ThroughputMonitor", "make_step_record", "validate_step_record",
           "STEP_RECORD_REQUIRED", "STEP_RECORD_FIELDS",
           "diag_signals", "diagnose_window", "DIAG_TERMS"]

# schema: required keys are always present; optional keys are present but
# may be null when the ingredient (sample counts, FLOPs) is unknown
STEP_RECORD_REQUIRED = {
    "ts": float, "step": int, "window_steps": int, "step_time_ms": float,
    "steps_per_sec": float, "data_wait_frac": float, "retraces": int,
}
STEP_RECORD_OPTIONAL = {
    "ips": float, "samples": int, "flops_per_step_est": float,
    "mfu_est": float, "device_mem_bytes": int, "device_mem_peak_bytes": int,
}
STEP_RECORD_FIELDS = set(STEP_RECORD_REQUIRED) | set(STEP_RECORD_OPTIONAL)

_DEFAULT_PEAK_FLOPS = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))


def make_step_record(*, step: int, window_steps: int, window_time_s: float,
                     samples: Optional[int] = None,
                     data_wait_s: float = 0.0,
                     flops_per_step: Optional[float] = None,
                     peak_flops: Optional[float] = None,
                     retraces: int = 0,
                     device_mem_bytes: Optional[int] = None,
                     device_mem_peak_bytes: Optional[int] = None) -> dict:
    """Build one schema-conformant step-window record. Degrades gracefully:
    a zero-length window yields zero rates, missing samples/FLOPs yield
    null ips/mfu — never a ZeroDivisionError."""
    window_steps = max(int(window_steps), 0)
    steps_per_sec = window_steps / window_time_s if window_time_s > 0 else 0.0
    ips = (float(samples) / window_time_s
           if samples and window_time_s > 0 else None)
    peak = peak_flops if peak_flops else _DEFAULT_PEAK_FLOPS
    mfu = (float(flops_per_step) * steps_per_sec / peak
           if flops_per_step and steps_per_sec > 0 and peak > 0 else None)
    return {
        "ts": time.time(),
        "step": int(step),
        "window_steps": window_steps,
        "step_time_ms": (1000.0 * window_time_s / window_steps
                         if window_steps else 0.0),
        "steps_per_sec": steps_per_sec,
        "ips": ips,
        "samples": int(samples) if samples else None,
        "data_wait_frac": (min(1.0, max(0.0, data_wait_s / window_time_s))
                           if window_time_s > 0 else 0.0),
        "flops_per_step_est": (float(flops_per_step)
                               if flops_per_step else None),
        "mfu_est": mfu,
        "retraces": int(retraces),
        "device_mem_bytes": (int(device_mem_bytes)
                             if device_mem_bytes is not None else None),
        "device_mem_peak_bytes": (int(device_mem_peak_bytes)
                                  if device_mem_peak_bytes is not None
                                  else None),
    }


def _sampled_device_mem():
    """(bytes_in_use summed, peak summed) across devices, or (None, None)
    when sampling found nothing (metrics disabled, no live arrays). One
    sampling pass refreshes ALL the gauges too."""
    mem = metrics_mod.update_device_memory_gauges()
    if not mem:
        return None, None
    return (sum(v["bytes_in_use"] for v in mem.values()),
            sum(v["peak_bytes"] for v in mem.values()))


# ---------------------------------------------------------------------------
# step-slowness diagnosis: decompose a window's wall time into the runtime's
# known cost terms from signals the registry already holds
# ---------------------------------------------------------------------------
#: the decomposition terms, each backed by named registry families (plus the
#: residual "unattributed" bucket diagnose_window adds)
DIAG_TERMS = ("data_wait", "host_dispatch", "device_compute", "collective",
              "compile", "checkpoint", "straggler_wait")

# term -> metric families whose cumulative seconds feed it (histogram sums
# and counters both work — _cum_seconds handles either)
_DIAG_FAMILIES = {
    "host_dispatch": ("op_time_seconds",),
    "device_compute": ("op_device_seconds",),
    "collective": ("collective_seconds",),
    "compile": ("xla_compile_seconds",),
    "checkpoint": ("checkpoint_save_seconds", "checkpoint_async_seconds"),
    "straggler_wait": ("ckpt_barrier_wait_seconds",),
}


def _cum_seconds(name: str) -> float:
    """Cumulative seconds accumulated by a family across all its series."""
    m = metrics_mod.default_registry().get(name)
    if m is None:
        return 0.0
    try:
        total = 0.0
        for v in m.snapshot()["values"]:
            total += float(v["sum"] if "sum" in v else v.get("value", 0.0))
        return total
    except Exception:
        return 0.0


#: newest diagnosis this process produced (any source: monitor window,
#: capture session, manual call) — the fleet digest picks it up so the
#: aggregator can show every host's dominant term
_last_diagnosis: Optional[dict] = None


def last_diagnosis() -> Optional[dict]:
    return _last_diagnosis


def diag_signals() -> dict:
    """Cumulative per-term seconds right now — capture once at a window's
    start and hand to :func:`diagnose_window` at its end."""
    sig = {}
    for term, fams in _DIAG_FAMILIES.items():
        sig[term] = sum(_cum_seconds(f) for f in fams)
    try:
        sig["data_wait"] = float(benchmark().reader.total_time)
    except Exception:
        sig["data_wait"] = 0.0
    return sig


def diagnose_window(begin: dict, wall_s: float, steps: int = 0,
                    step: Optional[int] = None, emit: bool = True) -> dict:
    """Decompose the window since ``begin`` (a :func:`diag_signals`
    snapshot) and name the dominant cost term.

    Terms are independent cumulative clocks, so they can overlap (device
    compute under async dispatch runs concurrently with host time) and a
    term's share is reported against the wall, clipped to [0, 1] — this is
    a ranking heuristic for "what should I look at first", not an exact
    accounting. Whatever the terms don't cover is ``unattributed`` (python/
    framework host time outside any instrumented clock). Emits one
    ``step_diagnosis`` event naming the dominant term unless ``emit`` is
    False."""
    end = diag_signals()
    terms = {t: max(0.0, end.get(t, 0.0) - begin.get(t, 0.0))
             for t in ("data_wait",) + tuple(_DIAG_FAMILIES)}
    accounted = sum(terms.values())
    wall_s = max(0.0, float(wall_s))
    terms["unattributed"] = max(0.0, wall_s - accounted)
    dominant = max(terms, key=terms.get) if wall_s > 0 else "unknown"
    dom_s = terms.get(dominant, 0.0)
    rec = {
        "wall_s": round(wall_s, 6),
        "steps": int(steps),
        "terms": {t: round(v, 6) for t, v in terms.items()},
        "dominant": dominant,
        "dominant_frac": (round(min(1.0, dom_s / wall_s), 4)
                          if wall_s > 0 else None),
    }
    if step is not None:
        rec["step"] = int(step)
    global _last_diagnosis
    _last_diagnosis = rec
    if emit:
        from . import events as events_mod
        events_mod.emit("step_diagnosis", **rec)
    return rec


def validate_step_record(rec: dict) -> dict:
    """Raise ValueError (naming every violation) unless `rec` conforms to
    the step-JSONL schema; returns the record for chaining."""
    problems = []
    if not isinstance(rec, dict):
        raise ValueError(f"step record must be a dict, got {type(rec)}")
    for key, ty in STEP_RECORD_REQUIRED.items():
        if key not in rec:
            problems.append(f"missing required key {key!r}")
        elif not isinstance(rec[key], (int, float)) or isinstance(rec[key], bool):
            problems.append(f"{key!r} must be numeric, got {type(rec[key])}")
    for key in STEP_RECORD_OPTIONAL:
        if key in rec and rec[key] is not None and (
                not isinstance(rec[key], (int, float))
                or isinstance(rec[key], bool)):
            problems.append(f"{key!r} must be numeric or null, "
                            f"got {type(rec[key])}")
    unknown = set(rec) - STEP_RECORD_FIELDS
    if unknown:
        problems.append(f"unknown keys {sorted(unknown)}")
    if not problems:
        if rec.get("window_steps", 0) < 0:
            problems.append("window_steps < 0")
        dwf = rec.get("data_wait_frac", 0.0)
        if not (0.0 <= dwf <= 1.0):
            problems.append(f"data_wait_frac {dwf} outside [0, 1]")
    if problems:
        raise ValueError("invalid step record: " + "; ".join(problems))
    return rec


class ThroughputMonitor:
    """hapi callback emitting one JSONL record per `window` train steps.

    Usage (hapi):
        model.fit(..., callbacks=[ThroughputMonitor(
            window=50, jsonl_path="steps.jsonl",
            flops_per_sample=3 * 4.09e9, samples_per_step=batch_size)])

    Or drive the hooks manually from a custom loop (`on_train_begin`, then
    `on_train_batch_begin`/`on_train_batch_end` per step, `on_train_end`).

    Data-wait time comes from the global `timer.benchmark()` reader
    averager, which the DataLoader iterators feed; retrace counts from the
    watchdog (whose warn window resets per epoch here — that is what turns
    `PADDLE_TPU_RETRACE_WARN` into "op X retraced N times in one epoch").
    """

    def __init__(self, window: int = 20, jsonl_path: Optional[str] = None,
                 flops_per_sample: Optional[float] = None,
                 flops_per_step: Optional[float] = None,
                 samples_per_step: Optional[int] = None,
                 peak_flops: Optional[float] = None,
                 emit: Optional[Callable[[dict], None]] = None,
                 diagnose: bool = True):
        self.window = max(int(window), 1)
        self.jsonl_path = jsonl_path
        self.flops_per_sample = flops_per_sample
        self.flops_per_step = flops_per_step
        self.samples_per_step = samples_per_step
        self.peak_flops = peak_flops or _DEFAULT_PEAK_FLOPS
        self.records: List[dict] = []
        self.diagnose = bool(diagnose)
        self.diagnoses: List[dict] = []
        self._emit = emit
        self._file = None
        self.model = None
        self.params = {}
        self._reset_window_state()
        self._global_step = 0

    # hapi Callback protocol (duck-typed, no base-class import)
    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def _reset_window_state(self):
        self._win_t0 = None
        self._win_steps = 0
        self._win_samples = 0
        self._reader_t0 = 0.0
        self._retrace_t0 = 0
        self._diag0 = None

    # -- hooks ---------------------------------------------------------------
    def on_train_begin(self, logs=None):
        self._global_step = 0
        self._reset_window_state()
        if self.jsonl_path and self._file is None:
            self._file = open(self.jsonl_path, "a")

    def on_epoch_begin(self, epoch, logs=None):
        get_watchdog().reset_window()

    def on_train_batch_begin(self, step, logs=None):
        if self._win_t0 is None:
            self._win_t0 = time.perf_counter()
            self._reader_t0 = benchmark().reader.total_time
            self._retrace_t0 = get_watchdog().total_retraces()
            if self.diagnose:
                self._diag0 = diag_signals()

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        if self.model is None:
            # manually-driven loop (no hapi fit, which notes its own
            # global step): feed /healthz liveness + the fleet digest here
            server_mod.note_step(self._global_step)
        self._win_steps += 1
        n = self.samples_per_step
        if n is None and isinstance(logs, dict):
            n = logs.get("num_samples")
        if n:
            self._win_samples += int(n)
        if self._win_steps >= self.window:
            self._flush_window()

    def on_epoch_end(self, epoch, logs=None):
        self._flush_window()

    def on_train_end(self, logs=None):
        self._flush_window()
        if self._file is not None:
            self._file.close()
            self._file = None

    # unused hooks (hapi CallbackList calls them all)
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass

    # -- emission ------------------------------------------------------------
    def _flush_window(self):
        if self._win_t0 is None or self._win_steps == 0:
            self._reset_window_state()
            return
        dt = time.perf_counter() - self._win_t0
        flops = self.flops_per_step
        if flops is None and self.flops_per_sample and self._win_steps:
            flops = (self.flops_per_sample * self._win_samples
                     / self._win_steps) if self._win_samples else None
        mem_bytes, mem_peak = _sampled_device_mem()
        rec = make_step_record(
            step=self._global_step,
            window_steps=self._win_steps,
            window_time_s=dt,
            samples=self._win_samples or None,
            data_wait_s=max(0.0, benchmark().reader.total_time
                            - self._reader_t0),
            flops_per_step=flops,
            peak_flops=self.peak_flops,
            retraces=get_watchdog().total_retraces() - self._retrace_t0,
            device_mem_bytes=mem_bytes,
            device_mem_peak_bytes=mem_peak)
        self.records.append(rec)
        if self.diagnose and self._diag0 is not None:
            self.diagnoses.append(diagnose_window(
                self._diag0, dt, steps=self._win_steps,
                step=self._global_step))
        line = json.dumps(rec)
        if self._file is not None:
            self._file.write(line + "\n")
            self._file.flush()
        if self._emit is not None:
            self._emit(rec)
        self._reset_window_state()
