"""Measured per-op device time from the jax.profiler (XPlane) trace.

PR 6's device-time split attributes device time per op by roofline
ESTIMATE (or wall-until-completion under ``PADDLE_TPU_DEVICE_TIME=sync``,
which serializes dispatch). This module adds the third, authoritative
mode: run a bounded ``jax.profiler`` capture session, parse the trace it
emits, and correlate backend execution events back to the host op spans —
``HostSpan.device_ns`` gains ``device_src="xplane"``.

Format choice: ``jax.profiler.stop_trace`` writes both the XPlane proto
and a TensorBoard chrome export (``*.trace.json.gz``) into
``<dir>/plugins/profile/<ts>/``. We parse the chrome export — stdlib
``gzip`` + ``json``, no tensorboard/tensorflow dependency, and its event
model (complete events with ``pid``/``tid``/``ts``/``dur`` microseconds)
is stable across jax versions.

Correlation model:

* Host lanes carry ``TraceMe`` annotations — the names ``RecordEvent``
  already emits (`profiler/utils.py`) plus, while a capture session is
  active, one annotation per eager op dispatch (`ops/_dispatch` checks
  :func:`annotating`). The k-th trace annotation named N is matched to the
  k-th collected host span named N (aligned from the newest — spans
  recorded before the trace started have no annotation).
* Work lanes carry backend execution events: on TPU the ``/device:TPU:n``
  process planes, on the CPU backend the thunk-executor threads (HLO op
  names like ``dot.3`` / ``broadcast_divide_fusion``). Infra markers
  (``Foo::Bar`` C++ methods, ``$``-prefixed python tracer frames) are
  filtered out.
* A span's measured device time is the summed overlap of work events with
  its annotation window (plus any work event whose args name the
  annotation — the XLA-metadata path on real TPU). Work can run on several
  executor lanes at once, so the sum is lane-time, not wall time; and
  async dispatch can slide work a little past its window — this is a
  measurement-based attribution, not a cycle-exact one. CPU CI exercises
  the full capture/parse/correlate path because jax's profiler records
  host TraceMe AND CPU-backend thunk execution.

On-demand capture: :class:`ProfileCapture` arms a bounded window around
the next N observed train steps (`server.note_step` drives it), with a
hard wall-clock cap so a stalled job cannot trace forever — the
``/profile?steps=N`` endpoint on the ObservabilityServer fronts it.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax

from . import device_time as _device_time
from . import events as _events_mod
from . import metrics as _metrics_mod
from .recorder import get_recorder, now_ns

__all__ = [
    "CaptureBusyError", "CaptureSession", "ProfileCapture",
    "default_capture", "annotating", "find_trace_file", "load_trace",
    "classify_lanes", "work_events", "correlate",
]

#: default hard wall-clock cap (seconds) on one capture session
DEFAULT_CAPTURE_TIMEOUT = 120.0

_REG = _metrics_mod.default_registry()
_M_CAPTURES = _REG.counter(
    "profile_captures_total",
    "on-demand profiler capture sessions by terminal status "
    "(complete / timeout / error)")

# True while a CaptureSession is recording: ops/_dispatch wraps each eager
# op in a TraceAnnotation so its name lands in the trace for correlation
_ANNOTATING = False


def annotating() -> bool:
    """Cheap flag for the dispatch hot path: wrap ops in TraceAnnotation?"""
    return _ANNOTATING


class CaptureBusyError(RuntimeError):
    """A capture session is already armed/recording (one at a time), or
    the host recorder is owned by an active Profiler window."""


# ---------------------------------------------------------------------------
# trace parsing
# ---------------------------------------------------------------------------
def find_trace_file(session_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under the jax session layout
    (``<dir>/plugins/profile/<ts>/``); also accepts a flat dir of traces."""
    pats = (os.path.join(session_dir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(session_dir, "*.trace.json.gz"))
    hits: List[str] = []
    for pat in pats:
        hits.extend(glob.glob(pat))
    return max(hits, key=os.path.getmtime) if hits else None


def load_trace(path: str) -> dict:
    """A chrome-trace dict from ``.trace.json.gz`` / plain ``.json``."""
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def _lane_meta(events: Sequence[dict]):
    """(process_names {pid: name}, thread_names {(pid, tid): name})."""
    procs: Dict[object, str] = {}
    threads: Dict[Tuple[object, object], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            procs[e.get("pid")] = str(args.get("name", ""))
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = str(args.get("name", ""))
    return procs, threads


def _is_infra(name: str) -> bool:
    """C++ infra markers and python-tracer frames — never op work."""
    return "::" in name or name.startswith("$")


def classify_lanes(events: Sequence[dict],
                   span_names: Sequence[str] = ()):
    """Split the trace's (pid, tid) lanes into host vs work.

    Host lanes: python threads carrying TraceMe annotations (named thread
    "python", ``$``-frame events, or one of the span names we are
    correlating). Work lanes: every lane of a ``/device:*`` process plus
    any remaining lane with at least one non-infra event (the CPU
    backend's executor threads). Returns (host_lanes, work_lanes) as sets
    of (pid, tid)."""
    procs, threads = _lane_meta(events)
    device_pids = {pid for pid, name in procs.items() if "/device:" in name}
    names = set(span_names)
    host: set = set()
    work: set = set()
    lane_events: Dict[Tuple[object, object], List[dict]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        lane_events.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    for lane, evs in lane_events.items():
        pid = lane[0]
        if pid in device_pids:
            work.add(lane)
            continue
        tname = threads.get(lane, "")
        if tname.startswith("python") \
                or any(e.get("name", "").startswith("$") for e in evs) \
                or (names and any(e.get("name") in names for e in evs)):
            host.add(lane)
        elif any(not _is_infra(e.get("name", "")) for e in evs):
            work.add(lane)
    return host, work


def work_events(events: Sequence[dict],
                span_names: Sequence[str] = (),
                lanes=None) -> List[dict]:
    """Backend execution events (work lanes, infra filtered), ts-sorted.
    `lanes` accepts a precomputed `classify_lanes` result so a caller that
    already classified does not pay a second full trace pass."""
    _, work = lanes if lanes is not None \
        else classify_lanes(events, span_names)
    names = set(span_names)
    out = [e for e in events
           if e.get("ph") == "X"
           and (e.get("pid"), e.get("tid")) in work
           and not _is_infra(e.get("name", ""))
           and e.get("name") not in names]
    out.sort(key=lambda e: e.get("ts", 0.0))
    return out


# train-step segment tags: named scopes pushed by the models/TrainStep
# (models/gpt.py, jit.TrainStep "loss"/"optimizer") plus the Pallas kernel
# custom-call names — matched as substrings of a work event's name and
# string args (the XLA op-metadata path; CPU traces carry no metadata, so
# there the breakdown degrades to "unattributed")
SEGMENT_TAGS = (
    ("attention", ("attention", "flash_", "sdpa")),
    ("mlp", ("mlp",)),
    # "ln" must stay delimited (bare "ln" is a substring of e.g.
    # "kernel_name"), but the delimiters need the autodiff spellings too:
    # backward LN ops are named ".../transpose(jvp(ln))/..."
    ("ln", ("/ln/", "(ln)", "jvp(ln", "layer_norm")),
    ("embed", ("embed",)),
    ("logits", ("logits",)),
    ("loss", ("loss", "softmax_ce", "cross_entropy")),
    ("optimizer", ("optimizer",)),
)

# autodiff markers XLA embeds in op_name metadata for backward ops
_BWD_MARKERS = ("transpose(", "/transpose[", "vjp(")


def _event_blob(e: dict) -> str:
    """name + every string arg of a work event, lowered — the haystack
    segment tags are matched against."""
    parts = [str(e.get("name", ""))]
    args = e.get("args")
    if isinstance(args, dict):
        parts.extend(v for v in args.values() if isinstance(v, str))
    return " ".join(parts).lower()


def segment_breakdown(events: Sequence[dict], lanes=None,
                      tags=SEGMENT_TAGS) -> dict:
    """Measured per-segment device time from a parsed trace.

    Classifies every backend work event into a train-step segment
    (attention/mlp/ln/embed/logits/loss/optimizer) by the named-scope tags
    XLA propagates into op metadata, splitting attention/mlp further into
    fwd vs bwd by the autodiff markers in the op_name path. Events with no
    recognizable metadata land in ``unattributed`` — on CPU traces (no
    XLA metadata in the chrome export) that is everything, and the block
    says so rather than guessing. Returns ``{"segments": {name:
    {"device_ms", "events", "frac"}}, "total_device_ms",
    "attributed_frac"}`` sorted by time.
    """
    works = work_events(events, lanes=lanes)
    total_us = 0.0
    seg_us: Dict[str, float] = {}
    seg_n: Dict[str, int] = {}
    for e in works:
        dur = float(e.get("dur", 0.0))
        if dur <= 0:
            continue
        total_us += dur
        blob = _event_blob(e)
        seg = None
        for name, needles in tags:
            if any(n in blob for n in needles):
                seg = name
                break
        if seg is None:
            seg = "unattributed"
        elif seg in ("attention", "mlp"):
            bwd = any(m in blob for m in _BWD_MARKERS)
            seg = f"{seg}_{'bwd' if bwd else 'fwd'}"
        seg_us[seg] = seg_us.get(seg, 0.0) + dur
        seg_n[seg] = seg_n.get(seg, 0) + 1
    out = {
        "segments": {
            k: {"device_ms": round(v / 1e3, 4),
                "events": seg_n[k],
                "frac": round(v / total_us, 4) if total_us else None}
            for k, v in sorted(seg_us.items(), key=lambda kv: -kv[1])},
        "total_device_ms": round(total_us / 1e3, 4),
        "attributed_frac": round(
            1.0 - seg_us.get("unattributed", 0.0) / total_us, 4)
        if total_us else None,
        "note": ("device-lane work events classified by XLA op-metadata "
                 "scope tags (jax.named_scope in the model + TrainStep); "
                 "fwd/bwd split by autodiff markers; 'unattributed' "
                 "covers events whose export carries no metadata (all of "
                 "them on CPU traces)"),
    }
    return out


def _args_name_match(e: dict, names: set) -> Optional[str]:
    """A work event whose args carry one of our annotation names (XLA
    op-metadata propagation on real TPU); returns the matched name."""
    args = e.get("args")
    if not isinstance(args, dict):
        return None
    for v in args.values():
        if isinstance(v, str) and v in names:
            return v
    return None


def correlate(spans, events: Sequence[dict]) -> dict:
    """Attribute measured device time from a parsed trace onto host spans.

    Mutates matched spans in place: ``device_ns`` becomes the measured
    lane-time, ``device_src`` becomes ``"xplane"``. Unmatched spans keep
    their estimate. Returns correlation stats including a per-op
    measured-vs-estimate table (``by_op``)."""
    span_list = list(spans)
    names = {s.name for s in span_list}
    lanes = classify_lanes(events, names)
    host, _ = lanes
    anns: Dict[str, List[dict]] = {}
    for e in events:
        if e.get("ph") == "X" and e.get("name") in names \
                and (e.get("pid"), e.get("tid")) in host:
            anns.setdefault(e["name"], []).append(e)
    for lst in anns.values():
        lst.sort(key=lambda e: e.get("ts", 0.0))
    works = work_events(events, names, lanes=lanes)

    # args-matched work (TPU metadata path): nearest annotation of the name
    arg_matched: Dict[int, List[dict]] = {}  # id(ann_event) -> [work events]
    plain_work: List[Tuple[float, float, dict]] = []  # (ts, end, event)
    for w in works:
        m = _args_name_match(w, names)
        cands = anns.get(m) if m else None
        if cands:
            nearest = min(cands, key=lambda a: abs(a.get("ts", 0.0)
                                                   - w.get("ts", 0.0)))
            arg_matched.setdefault(id(nearest), []).append(w)
        else:
            ts = w.get("ts", 0.0)
            plain_work.append((ts, ts + float(w.get("dur", 0.0)), w))

    # (window start, window end, annotation, span) pairs, aligned from the
    # newest per name: spans recorded before the trace started have no
    # annotation, extra annotations have no span
    by_name: Dict[str, List] = {}
    for s in span_list:
        by_name.setdefault(s.name, []).append(s)
    pairs: List[tuple] = []
    for name, sps in by_name.items():
        sps.sort(key=lambda s: s.start_ns)
        evs = anns.get(name, [])
        k = min(len(sps), len(evs))
        for s, a in zip(sps[-k:], evs[-k:]):
            w0 = a.get("ts", 0.0)
            pairs.append((w0, w0 + float(a.get("dur", 0.0)), a, s))
    # one forward cursor over the ts-sorted work events: windows processed
    # in start order, and an event that ended before window start can
    # never overlap a later window — near-linear instead of quadratic
    pairs.sort(key=lambda p: p[0])
    correlated = 0
    by_op: Dict[str, dict] = {}
    lo = 0
    for w0, w1, a, s in pairs:
        while lo < len(plain_work) and plain_work[lo][1] <= w0:
            lo += 1
        dev_us = 0.0
        i = lo
        while i < len(plain_work) and plain_work[i][0] < w1:
            ov = min(plain_work[i][1], w1) - max(plain_work[i][0], w0)
            if ov > 0:
                dev_us += ov
            i += 1
        for w in arg_matched.get(id(a), ()):
            dev_us += float(w.get("dur", 0.0))
        if dev_us <= 0:
            continue
        name = s.name
        row = by_op.setdefault(name, {"op": name, "calls": 0,
                                      "est_ms": 0.0, "xplane_ms": 0.0})
        row["calls"] += 1
        if s.device_src == "estimate" and s.device_ns:
            row["est_ms"] += s.device_ns / 1e6
        row["xplane_ms"] += dev_us / 1e3
        s.device_ns = int(dev_us * 1e3)
        s.device_src = "xplane"
        correlated += 1
    for row in by_op.values():
        row["est_ms"] = round(row["est_ms"], 4)
        row["xplane_ms"] = round(row["xplane_ms"], 4)
        row["xplane_vs_est"] = (round(row["xplane_ms"] / row["est_ms"], 3)
                                if row["est_ms"] > 0 else None)
    return {
        "spans": len(span_list),
        "correlated": correlated,
        "annotations": sum(len(v) for v in anns.values()),
        "work_events": len(works),
        "by_op": sorted(by_op.values(), key=lambda r: -r["xplane_ms"]),
    }


# ---------------------------------------------------------------------------
# capture session
# ---------------------------------------------------------------------------
class CaptureSession:
    """One jax.profiler trace window over the host recorder.

    ``start()`` clears and enables the recorder, starts the device trace,
    and flips :func:`annotating` so every eager op dispatch annotates the
    trace; ``stop()`` reverses all of it, parses the emitted trace,
    correlates spans, and returns (and writes) the summary. The recorder
    must be idle — an active Profiler RECORD window owns it
    (:class:`CaptureBusyError`)."""

    def __init__(self, session_dir: str):
        self.session_dir = session_dir
        self.started = False
        self.spans: list = []
        self._t0_ns = 0
        self._diag0: Optional[dict] = None

    def start(self):
        global _ANNOTATING
        rec = get_recorder()
        if rec.enabled:
            raise CaptureBusyError(
                "host recorder is already recording (Profiler window or "
                "another capture active)")
        os.makedirs(self.session_dir, exist_ok=True)
        jax.profiler.start_trace(self.session_dir)
        rec.clear()
        rec.enabled = True
        _ANNOTATING = True
        from . import monitor as _monitor
        self._diag0 = _monitor.diag_signals()
        self._t0_ns = now_ns()
        self.started = True
        return self

    def stop(self, steps: Optional[int] = None,
             status: str = "complete") -> dict:
        global _ANNOTATING
        rec = get_recorder()
        _ANNOTATING = False
        rec.enabled = False
        wall_s = max(0.0, (now_ns() - self._t0_ns) / 1e9)
        self.spans = rec.collect()
        trace_error = None
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            trace_error = f"{type(e).__name__}: {e}"
        self.started = False
        summary = self._summarize(wall_s, steps, status, trace_error)
        try:
            with open(os.path.join(self.session_dir, "summary.json"),
                      "w") as f:
                json.dump(summary, f, indent=1)
        except OSError:
            pass
        return summary

    def _summarize(self, wall_s: float, steps: Optional[int], status: str,
                   trace_error: Optional[str]) -> dict:
        from .statistic import StatisticData, summary_report
        summary = {
            "status": status,
            "ts": time.time(),
            "session_dir": self.session_dir,
            "wall_s": round(wall_s, 4),
            "steps": steps,
        }
        if trace_error:
            summary["trace_error"] = trace_error
        trace_path = find_trace_file(self.session_dir)
        summary["trace_path"] = trace_path
        if trace_path:
            try:
                doc = load_trace(trace_path)
                summary["correlation"] = correlate(
                    self.spans, doc.get("traceEvents", []))
                summary["segments"] = segment_breakdown(
                    doc.get("traceEvents", []))
            except Exception as e:
                summary["parse_error"] = f"{type(e).__name__}: {e}"
        summary["device_time"] = {
            "rows": _device_time.split_rows(self.spans),
            "mode": "xplane" if any(s.device_src == "xplane"
                                    for s in self.spans) else "estimate",
        }
        try:
            summary["summary_table"] = summary_report(
                StatisticData(self.spans))
        except Exception as e:
            summary["table_error"] = f"{type(e).__name__}: {e}"
        if self._diag0 is not None:
            try:
                from . import monitor as _monitor
                summary["diagnosis"] = _monitor.diagnose_window(
                    self._diag0, wall_s, steps=steps or 0)
            except Exception as e:
                summary["diagnosis_error"] = f"{type(e).__name__}: {e}"
        return summary


# ---------------------------------------------------------------------------
# on-demand armed capture (the /profile backend)
# ---------------------------------------------------------------------------
def _default_session_root() -> str:
    return os.environ.get(
        "PADDLE_TPU_PROFILE_DIR",
        os.path.join(tempfile.gettempdir(),
                     f"paddle_tpu_profile_{os.getpid()}"))


def capture_timeout() -> float:
    """Hard wall-clock cap on one capture (PADDLE_TPU_PROFILE_TIMEOUT)."""
    from ..utils.envparse import env_float
    return env_float("PADDLE_TPU_PROFILE_TIMEOUT", DEFAULT_CAPTURE_TIMEOUT)


class ProfileCapture:
    """Exactly-one-at-a-time capture armed around the next N train steps.

    `arm(steps=N)` -> the next `note_step` starts the trace, the N-th
    after that stops it and builds the summary. A `threading.Timer` at the
    hard cap finalizes a window the step flow never closes (stalled job,
    armed-but-idle loop) — a capture can never outlive the cap.

    While recording, every inter-`note_step` interval is bracketed in a
    ``train_step`` TraceAnnotation + host span (opened/closed on the
    training thread, which is the thread calling note_step): a loop whose
    whole step is ONE compiled executable emits no per-op eager spans, so
    without this a capture of the production path would correlate
    nothing — with it, the summary carries measured per-STEP device
    lane-time next to whatever per-op spans eager dispatch contributed."""

    def __init__(self):
        self._lock = threading.Lock()
        self.state = "idle"  # idle | armed | recording
        self._session: Optional[CaptureSession] = None
        self._steps = 0
        self._start_step: Optional[int] = None
        self._end_step: Optional[int] = None
        self._timer: Optional[threading.Timer] = None
        self._done = threading.Event()
        self._done.set()
        self._seq = 0
        self._step_ann = None      # open TraceAnnotation of the current step
        self._step_t0: Optional[int] = None
        self.last_summary: Optional[dict] = None

    def arm(self, steps: int, session_dir: Optional[str] = None,
            timeout_s: Optional[float] = None) -> dict:
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        cap = float(timeout_s) if timeout_s else capture_timeout()
        with self._lock:
            if self.state != "idle":
                raise CaptureBusyError(
                    f"capture already {self.state} "
                    f"(one session at a time)")
            if get_recorder().enabled:
                raise CaptureBusyError(
                    "host recorder is busy (Profiler window active)")
            self._seq += 1
            if session_dir is None:
                session_dir = os.path.join(
                    _default_session_root(),
                    f"session_{self._seq}_{int(time.time())}")
            self._session = CaptureSession(session_dir)
            self._steps = steps
            self._start_step = self._end_step = None
            self.state = "armed"
            self.last_summary = None
            self._done.clear()
            self._timer = threading.Timer(cap, self._on_timeout)
            self._timer.daemon = True
            self._timer.start()
            return {"status": "armed", "steps": steps,
                    "session_dir": session_dir, "timeout_s": cap}

    def on_step(self, step: int):
        """Drive the armed window; cheap no-op while idle. Never raises."""
        if self.state == "idle":
            return
        try:
            with self._lock:
                if self.state == "armed":
                    self._session.start()
                    self._start_step = int(step)
                    self._end_step = int(step) + self._steps
                    self.state = "recording"
                    self._open_step_span()
                elif self.state == "recording":
                    self._close_step_span(push=True)
                    if int(step) >= self._end_step:
                        self._finalize_locked("complete")
                    else:
                        self._open_step_span()
        except CaptureBusyError as e:
            with self._lock:
                if self.state == "armed":
                    self._abort_locked(f"{e}")
        except Exception as e:  # capture must never take down training
            with self._lock:
                if self.state != "idle":
                    self._abort_locked(f"{type(e).__name__}: {e}")

    def _open_step_span(self):
        """Open the next inter-step annotation (training thread)."""
        self._step_t0 = now_ns()
        try:
            self._step_ann = jax.profiler.TraceAnnotation("train_step")
            self._step_ann.__enter__()
        except Exception:
            self._step_ann = None

    def _close_step_span(self, push: bool):
        """Close the open step annotation; `push` records it as a
        ``train_step`` host span (skipped on timer-thread finalize, where
        no full step completed and exiting another thread's TraceMe is
        best-effort)."""
        ann, self._step_ann = self._step_ann, None
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        if push and self._step_t0 is not None:
            from .recorder import HostSpan
            rec = get_recorder()
            if rec.enabled:
                rec.push(HostSpan(
                    name="train_step", start_ns=self._step_t0,
                    end_ns=now_ns(), tid=threading.get_ident(),
                    event_type="ProfileStep"))
        self._step_t0 = None

    def _on_timeout(self):
        with self._lock:
            if self.state == "recording":
                self._close_step_span(push=False)
                self._finalize_locked("timeout")
            elif self.state == "armed":
                self._abort_locked("timed out before any step was observed",
                                   status="timeout")

    def _finalize_locked(self, status: str):
        self._close_step_span(push=False)  # no-op when already closed
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        steps_seen = 0
        if self._start_step is not None:
            steps_seen = min(self._steps,
                             max(0, self._end_step - self._start_step))
            if status == "timeout":
                steps_seen = 0  # unknown; the summary's wall_s is the truth
        try:
            summary = self._session.stop(
                steps=self._steps if status == "complete" else steps_seen,
                status=status)
        except Exception as e:
            summary = {"status": "error", "ts": time.time(),
                       "error": f"{type(e).__name__}: {e}",
                       "session_dir": self._session.session_dir}
            status = "error"
        self.last_summary = summary
        self.state = "idle"
        if _metrics_mod.enabled():
            _M_CAPTURES.inc(status=summary.get("status", status))
        _events_mod.emit(
            "profile_capture",
            severity="info" if status == "complete" else "warn",
            status=summary.get("status", status),
            session_dir=self._session.session_dir,
            correlated=(summary.get("correlation") or {}).get("correlated"))
        self._done.set()

    def _abort_locked(self, reason: str, status: str = "error"):
        self._close_step_span(push=False)
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        sess = self._session
        if sess is not None and sess.started:
            try:
                sess.stop(status=status)
            except Exception:
                pass
        self.last_summary = {"status": status, "ts": time.time(),
                             "error": reason,
                             "session_dir": sess.session_dir if sess
                             else None}
        self.state = "idle"
        if _metrics_mod.enabled():
            _M_CAPTURES.inc(status=status)
        _events_mod.emit("profile_capture", severity="warn", status=status,
                         error=reason)
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Block until the current capture finalizes; returns its summary
        (None if still in flight at `timeout`)."""
        if not self._done.wait(timeout):
            return None
        return self.last_summary

    def status(self) -> dict:
        with self._lock:
            st = {"state": self.state}
            if self.state != "idle" and self._session is not None:
                st["session_dir"] = self._session.session_dir
                st["steps"] = self._steps
                if self._end_step is not None:
                    st["end_step"] = self._end_step
            if self.last_summary is not None:
                st["last"] = self.last_summary
            return st


_default_capture = ProfileCapture()


def default_capture() -> ProfileCapture:
    """The process-wide armed-capture manager (`/profile`'s backend)."""
    return _default_capture
