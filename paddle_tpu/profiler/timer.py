"""Throughput timer (ips) — reference `python/paddle/profiler/timer.py`.

`Benchmark` tracks reader (data-wait) cost vs batch cost with moving averages
and reports instantaneous + summary ips, exactly the shape of the reference's
`Benchmark:325` / `benchmark():417` speed reporter that hapi and the
DataLoader hook into.

Degradation contract (audited): every accessor is safe with zero recorded
steps, zero recorded samples (`num_samples=None` throughout), a `step()`
stream that never saw a reader fetch, and `end()` without `begin()` — ips
degrades to 0.0 / falls back to steps/s, never ZeroDivisionError.
"""
from __future__ import annotations

import time
from typing import Optional


class TimeAverager:
    """Reference `timer.py:278`."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._total = 0.0
        self._cnt = 0
        self._total_samples = 0

    def record(self, usetime: float, num_samples: Optional[int] = None):
        self._total += usetime
        self._cnt += 1
        if num_samples:
            self._total_samples += num_samples

    @property
    def total_time(self) -> float:
        return self._total

    @property
    def count(self) -> int:
        return self._cnt

    @property
    def total_samples(self) -> int:
        return self._total_samples

    def get_average(self) -> float:
        return self._total / self._cnt if self._cnt else 0.0

    def get_ips_average(self) -> float:
        return self._total_samples / self._total if self._total else 0.0


class Benchmark:
    """Reference `timer.py:325`."""

    def __init__(self):
        self.reader = TimeAverager()
        self.batch = TimeAverager()
        self._step_start = None
        self._reader_start = None
        self.total_samples = 0
        self.total_time = 0.0
        self._begin_time = None

    def reset(self):
        """Zero both averagers and the run totals (window restart)."""
        self.reader.reset()
        self.batch.reset()
        self._step_start = None
        self._reader_start = None
        self.total_samples = 0
        self.total_time = 0.0
        self._begin_time = None

    # DataLoader hook: called around the fetch of each batch
    def before_reader(self):
        self._reader_start = time.perf_counter()

    def after_reader(self):
        if self._reader_start is not None:
            self.reader.record(time.perf_counter() - self._reader_start)
            self._reader_start = None

    def begin(self):
        self._begin_time = time.perf_counter()
        self._step_start = time.perf_counter()

    def step(self, num_samples: Optional[int] = None):
        """Close one step window. Works without a prior `begin()` (the first
        call then only arms the timer — there is no window to record yet)."""
        now = time.perf_counter()
        if self._step_start is not None:
            self.batch.record(now - self._step_start, num_samples)
            if num_samples:
                self.total_samples += num_samples
        self._step_start = now

    def end(self):
        if self._begin_time is not None:
            self.total_time = time.perf_counter() - self._begin_time

    def step_info(self, unit: str = "samples") -> str:
        batch_avg = self.batch.get_average()
        reader_avg = self.reader.get_average()
        ips = self.batch.get_ips_average()
        msg = (f"reader_cost: {reader_avg:.5f} s, batch_cost: {batch_avg:.5f} s")
        if ips:
            msg += f", ips: {ips:.2f} {unit}/s"
        elif batch_avg:
            # no sample counts ever recorded: steps/s is still meaningful
            msg += f", ips: {1.0 / batch_avg:.2f} steps/s"
        return msg

    def report(self) -> dict:
        batch_avg = self.batch.get_average()
        return {
            "reader_cost_avg_s": self.reader.get_average(),
            "batch_cost_avg_s": batch_avg,
            "ips": self.batch.get_ips_average(),
            "steps_per_sec": 1.0 / batch_avg if batch_avg else 0.0,
            "total_samples": self.total_samples,
            "total_time_s": self.total_time,
        }


_benchmark = Benchmark()


def benchmark() -> Benchmark:
    """Global speed reporter (reference `timer.py:417`)."""
    return _benchmark
