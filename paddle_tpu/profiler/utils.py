"""RecordEvent + result loading.

Reference parity: `python/paddle/profiler/utils.py:31` (RecordEvent
ContextDecorator), `:125` (load_profiler_result), `:153` (wrap_optimizers).
Each span is recorded to the host recorder AND annotated into any active
jax.profiler device trace (`jax.profiler.TraceAnnotation` — the XLA analog of
nvtx ranges the reference emits for CUPTI correlation).
"""
from __future__ import annotations

import json
import threading
from contextlib import ContextDecorator
from typing import Optional

import jax

from .recorder import HostSpan, get_recorder, now_ns


class TracerEventType:
    Operator = "Operator"
    Dataloader = "Dataloader"
    ProfileStep = "ProfileStep"
    UserDefined = "UserDefined"
    Forward = "Forward"
    Backward = "Backward"
    Optimization = "Optimization"
    Communication = "Communication"


class RecordEvent(ContextDecorator):
    """RAII profiling span (reference `utils.py:31` / C++ `RecordEvent`)."""

    def __init__(self, name: str, event_type: str = TracerEventType.UserDefined):
        self.name = name
        self.event_type = event_type
        self._start = None
        self._jax_ann = None
        self._pushed = False

    def begin(self):
        rec = get_recorder()
        self._start = now_ns()
        if rec.enabled:
            rec.span_stack().append(self.name)
            self._pushed = True
            try:
                self._jax_ann = jax.profiler.TraceAnnotation(self.name)
                self._jax_ann.__enter__()
            except Exception:
                self._jax_ann = None

    def end(self):
        if self._start is None:
            return
        rec = get_recorder()
        if self._jax_ann is not None:
            self._jax_ann.__exit__(None, None, None)
            self._jax_ann = None
        # pop even if the record window closed mid-span, else the thread's
        # stack leaks the entry and later spans get a stale parent
        if self._pushed:
            stack = rec.span_stack()
            if self.name in stack:
                stack.reverse()
                stack.remove(self.name)
                stack.reverse()
            self._pushed = False
        if rec.enabled:
            stack = rec.span_stack()
            parent = stack[-1] if stack else None
            rec.push(HostSpan(name=self.name, start_ns=self._start,
                              end_ns=now_ns(), tid=threading.get_ident(),
                              event_type=self.event_type, parent=parent))
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(filename: str):
    """Load a chrome-trace JSON exported by Profiler.export (`utils.py:125`)."""
    with open(filename) as f:
        return json.load(f)


def wrap_optimizers():
    """No-op for parity: optimizer.step is already spanned via RecordEvent in
    Profiler-enabled training loops (reference monkey-patches optimizers)."""
    return None
