"""Span aggregation + text report.

Reference parity: `python/paddle/profiler/profiler_statistic.py` (SortedKeys,
StatisticData, per-event-type and per-name tables with count/total/avg/max/min
and ratio columns).
"""
from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional

from .recorder import HostSpan


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    Calls = 4


class _Item:
    __slots__ = ("name", "calls", "total_ns", "max_ns", "min_ns",
                 "device_ns", "device_src")

    def __init__(self, name):
        self.name = name
        self.calls = 0
        self.total_ns = 0
        self.max_ns = 0
        self.min_ns = None
        self.device_ns = 0       # summed device-side time (0 = none seen)
        self.device_src = None   # "measured" | "estimate" | None

    def add(self, span: HostSpan):
        d = span.dur_ns
        self.calls += 1
        self.total_ns += d
        self.max_ns = max(self.max_ns, d)
        self.min_ns = d if self.min_ns is None else min(self.min_ns, d)
        if span.device_ns is not None:
            self.device_ns += span.device_ns
            # the best span upgrades the row's provenance label
            # (estimate < measured < xplane — device_time.SRC_PRIORITY)
            from .device_time import SRC_PRIORITY
            if SRC_PRIORITY.get(span.device_src, 0) \
                    > SRC_PRIORITY.get(self.device_src, -1):
                self.device_src = span.device_src

    @property
    def avg_ns(self):
        return self.total_ns / self.calls if self.calls else 0


class StatisticData:
    """Aggregates host spans by name and event type."""

    def __init__(self, spans: List[HostSpan]):
        self.spans = spans
        self.by_name: Dict[str, _Item] = {}
        self.by_type: Dict[str, _Item] = {}
        for s in spans:
            self.by_name.setdefault(s.name, _Item(s.name)).add(s)
            self.by_type.setdefault(s.event_type, _Item(s.event_type)).add(s)
        if spans:
            self.wall_ns = (max(s.end_ns for s in spans)
                            - min(s.start_ns for s in spans))
        else:
            self.wall_ns = 0


_SORT_ATTR = {
    SortedKeys.CPUTotal: "total_ns",
    SortedKeys.CPUAvg: "avg_ns",
    SortedKeys.CPUMax: "max_ns",
    SortedKeys.CPUMin: "min_ns",
    SortedKeys.Calls: "calls",
}


def _fmt(ns: float, unit: str) -> str:
    div = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}[unit]
    return f"{ns / div:.3f}"


def summary_report(data: StatisticData, sorted_by: Optional[SortedKeys] = None,
                   time_unit: str = "ms") -> str:
    sorted_by = sorted_by or SortedKeys.CPUTotal
    attr = _SORT_ATTR[sorted_by]
    rows = sorted(data.by_name.values(),
                  key=lambda it: getattr(it, attr) or 0, reverse=True)
    name_w = max([len(r.name) for r in rows], default=4)
    name_w = max(name_w, 4)
    # the device column appears only when spans carried device attribution
    # (host time = dispatch latency; device time = execution, measured or
    # roofline-estimated — see profiler/device_time.py)
    has_device = any(r.device_ns for r in rows)
    header = (f"{'Name':<{name_w}}  {'Calls':>7}  {'Total(' + time_unit + ')':>12}  "
              f"{'Avg(' + time_unit + ')':>12}  {'Max(' + time_unit + ')':>12}  "
              f"{'Min(' + time_unit + ')':>12}  {'Ratio(%)':>8}")
    if has_device:
        header += f"  {'Dev(' + time_unit + ')':>12}  {'DevSrc':>8}"
    lines = ["-" * len(header), header, "-" * len(header)]
    total = sum(r.total_ns for r in rows) or 1
    for r in rows:
        line = (
            f"{r.name:<{name_w}}  {r.calls:>7}  {_fmt(r.total_ns, time_unit):>12}  "
            f"{_fmt(r.avg_ns, time_unit):>12}  {_fmt(r.max_ns, time_unit):>12}  "
            f"{_fmt(r.min_ns or 0, time_unit):>12}  {100 * r.total_ns / total:>8.2f}")
        if has_device:
            line += (f"  {_fmt(r.device_ns, time_unit):>12}  "
                     f"{r.device_src or '-':>8}")
        lines.append(line)
    lines.append("-" * len(header))
    lines.append(f"Wall clock: {_fmt(data.wall_ns, time_unit)} {time_unit}; "
                 f"{len(data.spans)} spans, {len(data.by_name)} distinct names")
    return "\n".join(lines)
