"""Host event recorder: thread-local span buffers merged on collect.

Reference parity: `paddle/fluid/platform/profiler/host_event_recorder.h`
(thread-local ring buffers of RecordEvent spans) + `event_node.cc` (merge into
an event tree). Here: a per-thread list of completed spans; `collect()` drains
all threads.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class HostSpan:
    name: str
    start_ns: int
    end_ns: int
    tid: int
    event_type: str = "UserDefined"
    parent: Optional[str] = None

    @property
    def dur_ns(self) -> int:
        return self.end_ns - self.start_ns


class HostEventRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._buffers = {}          # tid -> list[HostSpan]
        self._tls = threading.local()
        self.enabled = False

    def _buf(self) -> List[HostSpan]:
        buf = getattr(self._tls, "buf", None)
        if buf is None:
            buf = []
            self._tls.buf = buf
            with self._lock:
                self._buffers[threading.get_ident()] = buf
        return buf

    def push(self, span: HostSpan):
        if self.enabled:
            self._buf().append(span)

    def collect(self) -> List[HostSpan]:
        with self._lock:
            out = []
            for buf in self._buffers.values():
                out.extend(buf)
        out.sort(key=lambda s: s.start_ns)
        return out

    def clear(self):
        with self._lock:
            for buf in self._buffers.values():
                buf.clear()

    # active-span stack for nesting info
    def span_stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st


_recorder = HostEventRecorder()


def get_recorder() -> HostEventRecorder:
    return _recorder


def now_ns() -> int:
    return time.perf_counter_ns()
