"""Host event recorder: thread-local span buffers merged on collect.

Reference parity: `paddle/fluid/platform/profiler/host_event_recorder.h`
(thread-local ring buffers of RecordEvent spans) + `event_node.cc` (merge into
an event tree). Here: a per-thread buffer of completed spans; `collect()`
DRAINS all threads' buffers atomically per-thread — each buffer carries its
own lock, `push()` appends under it, and `collect()` swaps the span list out
under the same lock, so a span recorded concurrently with a collect lands in
either this batch or the next, never lost and never duplicated.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class HostSpan:
    name: str
    start_ns: int
    end_ns: int
    tid: int
    event_type: str = "UserDefined"
    parent: Optional[str] = None
    args: Optional[dict] = None   # op metadata: shapes/dtypes/bytes estimate
    device_ns: Optional[int] = None   # device-side execution time
    device_src: Optional[str] = None  # "estimate" | "measured" (device_time)
    #                                 # | "xplane" (xplane.correlate)

    @property
    def dur_ns(self) -> int:
        return self.end_ns - self.start_ns


class _ThreadBuffer:
    __slots__ = ("lock", "spans", "owner", "dropped")

    def __init__(self):
        self.lock = threading.Lock()
        self.spans: List[HostSpan] = []
        self.owner = threading.get_ident()
        self.dropped = False  # pruned from the registry; do not reuse


class HostEventRecorder:
    def __init__(self):
        self._lock = threading.Lock()   # guards the buffer REGISTRY only
        # keyed by buffer identity, NOT thread ident: the OS reuses thread
        # idents, and keying by ident let a new thread's buffer overwrite a
        # dead thread's registry entry while it still held un-collected
        # spans (silent span loss under churning worker threads)
        self._buffers: Dict[int, _ThreadBuffer] = {}
        self._tls = threading.local()
        self.enabled = False

    def _buf(self) -> _ThreadBuffer:
        buf = getattr(self._tls, "buf", None)
        # `dropped` covers a thread the prune misjudged as dead (a foreign
        # thread invisible to threading.enumerate()): it re-registers a
        # fresh buffer instead of pushing into the unreachable old one
        if buf is None or buf.dropped:
            buf = _ThreadBuffer()
            self._tls.buf = buf
            with self._lock:
                self._buffers[id(buf)] = buf
        return buf

    def push(self, span: HostSpan):
        if self.enabled:
            while True:
                buf = self._buf()
                with buf.lock:
                    # re-checked under the lock: a concurrent collect() may
                    # have pruned this buffer between _buf() and here (a
                    # live thread misjudged dead) — appending would orphan
                    # the span, so force a fresh registration instead
                    if not buf.dropped:
                        buf.spans.append(span)
                        return
                self._tls.buf = None

    def collect(self) -> List[HostSpan]:
        """Drain every thread's completed spans (sorted by start time).
        Draining semantics: a second collect() returns only spans recorded
        after the first one. Buffers of threads that have exited are pruned
        AFTER their drain (a dead thread cannot push again), bounding
        registry growth under thread churn."""
        with self._lock:
            items = list(self._buffers.items())
        live = {t.ident for t in threading.enumerate()}
        out: List[HostSpan] = []
        dead = []
        for key, buf in items:
            with buf.lock:
                out.extend(buf.spans)
                buf.spans.clear()
                if buf.owner not in live:
                    buf.dropped = True  # owner re-registers if misjudged
                    dead.append(key)
        if dead:
            with self._lock:
                for key in dead:
                    self._buffers.pop(key, None)
        out.sort(key=lambda s: s.start_ns)
        return out

    def clear(self):
        with self._lock:
            bufs = list(self._buffers.values())
        for buf in bufs:
            with buf.lock:
                buf.spans.clear()

    # active-span stack for nesting info
    def span_stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st


_recorder = HostEventRecorder()


def get_recorder() -> HostEventRecorder:
    return _recorder


def now_ns() -> int:
    return time.perf_counter_ns()
