"""Host event recorder: thread-local span buffers merged on collect.

Reference parity: `paddle/fluid/platform/profiler/host_event_recorder.h`
(thread-local ring buffers of RecordEvent spans) + `event_node.cc` (merge into
an event tree). Here: a per-thread buffer of completed spans; `collect()`
DRAINS all threads' buffers atomically per-thread — each buffer carries its
own lock, `push()` appends under it, and `collect()` swaps the span list out
under the same lock, so a span recorded concurrently with a collect lands in
either this batch or the next, never lost and never duplicated.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class HostSpan:
    name: str
    start_ns: int
    end_ns: int
    tid: int
    event_type: str = "UserDefined"
    parent: Optional[str] = None
    args: Optional[dict] = None   # op metadata: shapes/dtypes/bytes estimate

    @property
    def dur_ns(self) -> int:
        return self.end_ns - self.start_ns


class _ThreadBuffer:
    __slots__ = ("lock", "spans")

    def __init__(self):
        self.lock = threading.Lock()
        self.spans: List[HostSpan] = []


class HostEventRecorder:
    def __init__(self):
        self._lock = threading.Lock()   # guards the buffer REGISTRY only
        self._buffers: Dict[int, _ThreadBuffer] = {}
        self._tls = threading.local()
        self.enabled = False

    def _buf(self) -> _ThreadBuffer:
        buf = getattr(self._tls, "buf", None)
        if buf is None:
            buf = _ThreadBuffer()
            self._tls.buf = buf
            with self._lock:
                self._buffers[threading.get_ident()] = buf
        return buf

    def push(self, span: HostSpan):
        if self.enabled:
            buf = self._buf()
            with buf.lock:
                buf.spans.append(span)

    def collect(self) -> List[HostSpan]:
        """Drain every thread's completed spans (sorted by start time).
        Draining semantics: a second collect() returns only spans recorded
        after the first one."""
        with self._lock:
            bufs = list(self._buffers.values())
        out: List[HostSpan] = []
        for buf in bufs:
            with buf.lock:
                out.extend(buf.spans)
                buf.spans.clear()
        out.sort(key=lambda s: s.start_ns)
        return out

    def clear(self):
        with self._lock:
            bufs = list(self._buffers.values())
        for buf in bufs:
            with buf.lock:
                buf.spans.clear()

    # active-span stack for nesting info
    def span_stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st


_recorder = HostEventRecorder()


def get_recorder() -> HostEventRecorder:
    return _recorder


def now_ns() -> int:
    return time.perf_counter_ns()
