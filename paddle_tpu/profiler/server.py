"""ObservabilityServer: the runtime's HTTP face — /metrics /snapshot
/healthz /events on a stdlib daemon-thread server.

PR 2 built the registry and exporters but left scraping to "snapshot into
bench JSON"; a live job was still opaque. This serves the same process-wide
surfaces over plain HTTP (http.server, zero deps):

    /metrics    Prometheus text from the default registry; on a fleet's
                rank 0 (or a supervisor) each scrape first collect()s the
                FleetAggregator, so fleet_* families arrive host-labeled
    /snapshot   one JSON object: metrics snapshot, watchdog snapshot (incl.
                compile attribution), liveness, fleet view, recent events
    /healthz    step liveness: 200 {"status": "healthy"} while steps keep
                arriving, 503 {"status": "stalled"} once the last observed
                step is older than PADDLE_TPU_HEALTH_STALL_SEC (default
                300; "starting" before the first step)
    /events     recent unified-event-log entries (?kind=...&n=...)
    /profile    on-demand deep profiling: ?steps=N arms a bounded capture
                window around the next N train steps (jax.profiler trace +
                host spans, correlated by profiler/xplane.py) and returns
                the session summary; 409 while a session is in flight,
                hard wall-clock cap PADDLE_TPU_PROFILE_TIMEOUT
    /controller the fleet controller's live decision state (policies,
                streaks, evicted host, recent controller_decision
                records; with HA election, the `leader` block carries
                leader id / term / lease age / standby count and
                `is_leader` says whether THIS process decides); 404
                when no controller runs in this process
    /requests   serving introspection: live + recently-completed request
                traces (per-request phase breakdown from
                profiler/reqtrace.py) and the engine's per-iteration
                snapshot ring; 404 when no engine runs in this process
    /slo        serving SLO plane (profiler/slo.py): targets, sliding-
                window p50/p95/p99 per signal, current breach status
    /generate   POST {"prompt": [token ids], "max_new_tokens": N,
                sampling knobs...} -> generated tokens + latency
                attribution from the live engine. Sheds instead of
                hanging: 503 JSON when the engine is wedged past the
                /healthz stall threshold (or closed/absent), 429 with
                the queue depth when admission is saturated

Opt-in: set `PADDLE_TPU_METRICS_PORT` (0 = pick a free port) and the entry
points auto-start it — `Model.fit`, `bench.py`, and `tools/elastic_run.py`
(the supervisor serves on `PADDLE_TPU_SUPERVISOR_METRICS_PORT`, default
port+1, because the trainer child owns the configured port on the same
host; the supervisor's server survives trainer relaunches, so its /healthz
shows the restart gap as a growing step age).

Liveness is fed by `note_step()`, called by the fit loop / ThroughputMonitor
/ bench timed loops; the first note also publishes
`relaunch_to_first_step_seconds` and later notes drive the FleetReporter's
digest publication when one is installed.
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from . import compile_watch as _compile_watch
from . import events as _events_mod
from . import health as _health_mod
from . import metrics as _metrics_mod
from . import xplane as _xplane_mod
from .watchdog import get_watchdog

__all__ = ["ObservabilityServer", "maybe_start_server", "note_step",
           "liveness", "get_server", "stop_server"]

DEFAULT_STALL_SEC = 300.0

# module-level liveness: {step, ts(monotonic), wall_ts}
_liveness_lock = threading.Lock()
_liveness = {"step": None, "ts": None, "wall_ts": None}
_reporter = None  # FleetReporter installed by maybe_start_server
_server: Optional["ObservabilityServer"] = None


def note_step(step: int):
    """Record train-loop progress. Cheap, idempotent per step index (a
    second caller reporting the same step is ignored; a SMALLER step means
    a new training run started in this process), and never raises."""
    global _liveness
    step = int(step)
    with _liveness_lock:
        last = _liveness["step"]
        if last is not None and step == last:
            return  # a second caller reporting the same step
        first = last is None
        # step < last means a NEW training run in this process (a fresh
        # fit, an in-process elastic re-entry): liveness follows it
        _liveness["step"] = step
        _liveness["ts"] = time.monotonic()
        _liveness["wall_ts"] = time.time()
    if first:
        _compile_watch.note_first_step()
    rep = _reporter
    if rep is not None:
        rep.note_step(step)
    # drive any armed /profile capture window (cheap no-op while idle;
    # on_step itself never raises)
    _xplane_mod.default_capture().on_step(step)


def liveness(stall_after: Optional[float] = None) -> dict:
    """{"status": healthy|stalled|starting, "last_step", "last_step_age_s",
    "stall_after_s"} — the /healthz payload."""
    if stall_after is None:
        from ..utils.envparse import env_float
        stall_after = env_float("PADDLE_TPU_HEALTH_STALL_SEC",
                                DEFAULT_STALL_SEC)
    with _liveness_lock:
        step, ts = _liveness["step"], _liveness["ts"]
    if step is None:
        return {"status": "starting", "last_step": None,
                "last_step_age_s": None, "stall_after_s": stall_after}
    age = time.monotonic() - ts
    return {"status": "stalled" if age > stall_after else "healthy",
            "last_step": step, "last_step_age_s": round(age, 3),
            "stall_after_s": stall_after}


class ObservabilityServer:
    """One ThreadingHTTPServer on a daemon thread.

    `aggregator` (a fleet.telemetry.FleetAggregator) makes /metrics and
    /snapshot fleet-aware; without one they serve this process only."""

    def __init__(self, registry=None, aggregator=None,
                 stall_after: Optional[float] = None):
        self.registry = registry or _metrics_mod.default_registry()
        self.aggregator = aggregator
        self.stall_after = stall_after
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    # -- endpoint payloads ---------------------------------------------------
    @staticmethod
    def _audit_reports() -> list:
        # lazy: analysis imports profiler.metrics/events; importing it at
        # module scope here would be a cycle. A snapshot must also never
        # fail because the analysis package (optional at runtime) does.
        try:
            from ..analysis import recent_reports
            return recent_reports()
        except Exception:
            return []

    def _collect_fleet(self):
        if self.aggregator is None:
            return
        try:
            self.aggregator.collect()
        except Exception:
            pass  # a store hiccup must not fail the scrape

    def metrics_text(self) -> str:
        self._collect_fleet()
        return self.registry.to_prometheus_text()

    def snapshot(self) -> dict:
        """One JSON blob for dashboards: metrics + watchdog + compile
        attribution + liveness + health + the events tail + the newest
        static program-audit reports (e.g. the serving engine's fused
        decode executable) + optional fleet view."""
        self._collect_fleet()
        # refresh the device-memory gauges so the snapshot's watermark is
        # scrape-time, not last-step-record time
        _metrics_mod.update_device_memory_gauges(self.registry)
        snap = {
            "metrics": self.registry.snapshot(),
            "watchdog": get_watchdog().snapshot(),
            "compile_attribution": _compile_watch.summary(),
            "liveness": liveness(self.stall_after),
            "health": _health_mod.snapshot(),
            "events_tail": _events_mod.recent(50),
            "program_audit": self._audit_reports(),
            "ts": time.time(),
        }
        if self.aggregator is not None:
            snap["fleet"] = self.aggregator.snapshot()
        return snap

    def profile(self, query: dict) -> (int, dict):
        """The `/profile` endpoint body: (http status, payload).

        `?steps=N` arms an on-demand capture around the next N train steps
        and (by default) blocks until it finalizes — one curl profiles a
        live job with zero restarts. Exactly one session at a time
        (concurrent requests get 409); the hard wall-clock cap
        (`PADDLE_TPU_PROFILE_TIMEOUT`, `&timeout=S` to shrink it) bounds
        the block even when the job is stalled. `&wait=0` returns the
        armed ack immediately; without `steps` the current/last session
        status is returned."""
        cap = _xplane_mod.default_capture()
        raw_steps = query.get("steps", [None])[0]
        if raw_steps is None:
            return 200, cap.status()
        try:
            steps = int(raw_steps)
            if steps < 1:
                raise ValueError
        except ValueError:
            return 400, {"error": f"steps={raw_steps!r} must be a "
                                  f"positive integer"}
        timeout_s = None
        raw_timeout = query.get("timeout", [None])[0]
        if raw_timeout is not None:
            try:
                timeout_s = float(raw_timeout)
            except ValueError:
                return 400, {"error": f"timeout={raw_timeout!r} must be "
                                      f"a number of seconds"}
        wait = query.get("wait", ["1"])[0] not in ("0", "false", "no")
        try:
            ack = cap.arm(steps, timeout_s=timeout_s)
        except _xplane_mod.CaptureBusyError as e:
            return 409, {"error": str(e), "status": cap.status()}
        if not wait:
            return 202, ack
        # the timer finalizes at the cap no matter what, so this bound is
        # a backstop against a wedged finalize, not the real limit
        summary = cap.wait((timeout_s or _xplane_mod.capture_timeout()) + 30)
        if summary is None:
            return 504, {"error": "capture did not finalize in time",
                         "status": cap.status()}
        return 200, summary

    def controller_status(self) -> (int, dict):
        """The `/controller` endpoint: the fleet controller's live
        decision state (status 200), or 404 when no controller is
        attached to this process (the flag lives on one supervisor)."""
        try:
            from ..distributed.fleet.controller import get_controller
            ctl = get_controller()
        except Exception:
            ctl = None
        if ctl is None:
            return 404, {"error": "no fleet controller attached to this "
                                  "process (tools/elastic_run.py "
                                  "--controller runs one)"}
        return 200, ctl.status()

    # -- serving introspection endpoints -------------------------------------
    @staticmethod
    def _engine(name: Optional[str] = None):
        """The live ServingEngine, WITHOUT importing the inference stack
        from a scrape: if serving was never imported in this process there
        is no engine to find (and no reason to pull jax in)."""
        import sys
        mod = sys.modules.get("paddle_tpu.inference.serving")
        if mod is None:
            return None
        try:
            return mod.current_engine(name)
        except Exception:
            return None

    def requests_payload(self, query: dict) -> (int, dict):
        """`/requests`: live + recently-completed per-request phase
        breakdowns and the engine's per-iteration introspection ring."""
        raw_n = query.get("n", ["50"])[0]
        try:
            n = int(raw_n)
        except ValueError:
            return 400, {"error": f"n={raw_n!r} must be an integer"}
        eng = self._engine(query.get("model", [None])[0])
        if eng is None:
            return 404, {"error": "no serving engine in this process"}
        return 200, eng.requests_snapshot(n)

    def slo_payload(self, query: dict) -> (int, dict):
        """`/slo`: targets, sliding-window quantiles per signal, breach
        status. Falls back to the process's most recent SLO tracker when
        the engine itself is gone (post-close scrape)."""
        eng = self._engine(query.get("model", [None])[0])
        if eng is not None:
            return 200, eng.slo.snapshot()
        from .slo import current_snapshot
        snap = current_snapshot()
        if snap is None:
            return 404, {"error": "no serving SLO tracker in this "
                                  "process"}
        return 200, snap

    def generate_payload(self, body: bytes) -> (int, dict):
        """`/generate` (POST): one-call HTTP inference against the live
        engine. Routes by the optional `model` body field when several
        engines share the process. Sheds instead of hanging: 503 with a
        JSON error when the engine is wedged past the /healthz stall
        threshold (or closed / absent / suspended — suspended answers
        carry `retry_after_s`, surfaced as a Retry-After header), 429
        with the queue depth when admission is saturated
        (`PADDLE_TPU_SERVING_QUEUE_LIMIT` deep)."""
        from ..utils.envparse import env_int
        try:
            req = json.loads(body.decode() or "{}")
        except (ValueError, UnicodeDecodeError) as e:
            req = None  # defer the 400: absent-engine 503 wins
        model = req.get("model") if isinstance(req, dict) else None
        eng = self._engine(model)
        if eng is None:
            if model is not None:
                return 503, {"error": f"no serving engine named "
                                      f"{model!r} in this process",
                             "model": model}
            return 503, {"error": "no serving engine in this process"}
        if req is None:
            return 400, {"error": "request body is not JSON"}
        if eng._closed:
            return 503, {"error": "serving engine is closed",
                         "model": eng.name}
        if eng.wedged(self.stall_after):
            return 503, {"error": "serving engine is wedged (no decode "
                                  "progress past the stall threshold)",
                         "model": eng.name,
                         "stall_after_s": self.stall_after or liveness()
                         .get("stall_after_s")}
        if getattr(eng, "_suspended", None):
            return 503, {"error": "serving engine is suspended "
                                  f"({eng._suspended.get('reason')})",
                         "model": eng.name,
                         "retry_after_s":
                             eng._suspended.get("retry_after_s")}
        limit = env_int("PADDLE_TPU_SERVING_QUEUE_LIMIT", 64)
        depth = eng.queue_depth()
        if limit > 0 and depth >= limit:
            return 429, {"error": "admission queue saturated",
                         "model": eng.name, "queue_depth": depth,
                         "limit": limit}
        prompt = req.get("prompt")
        if not isinstance(prompt, list) or \
                not all(isinstance(t, int) for t in prompt):
            return 400, {"error": "'prompt' must be a list of token ids"}
        sampling = None
        sp_keys = {k: req[k] for k in ("temperature", "top_k", "top_p",
                                       "seed") if k in req}
        if sp_keys:
            try:
                from ..inference.sampling import SamplingParams
                sampling = SamplingParams(**sp_keys)
            except (TypeError, ValueError) as e:
                return 400, {"error": f"bad sampling params: {e}"}
        try:
            out = eng.generate(
                prompt,
                max_new_tokens=int(req.get("max_new_tokens", 16)),
                sampling=sampling,
                timeout=float(req.get("timeout", 120.0)))
        except (TypeError, ValueError) as e:
            return 400, {"error": str(e)}
        except TimeoutError as e:
            return 504, {"error": str(e)}
        except RuntimeError as e:
            payload = {"error": str(e), "model": eng.name}
            if getattr(e, "retry_after_s", None) is not None:
                payload["retry_after_s"] = e.retry_after_s
            return 503, payload
        return 200, out

    def healthz(self) -> dict:
        h = liveness(self.stall_after)
        # serving liveness counts too: a running engine holding work
        # without a completed decode iteration inside the stall window
        # flips 503 `stalled` just like a training loop that stopped
        # stepping (lazy module lookup — never imports the inference
        # stack from a scrape)
        import sys
        mod = sys.modules.get("paddle_tpu.inference.serving")
        if mod is not None:
            try:
                serving = {}
                for eng in mod.live_engines():
                    wedged = eng.wedged(self.stall_after)
                    serving[eng.name] = {
                        "pending": eng.pending(),
                        "last_progress_age_s":
                            round(eng.last_progress_age(), 3),
                        "wedged": wedged,
                        "suspended": bool(eng._suspended)}
                    if wedged:
                        h["status"] = "stalled"
                        h["stalled_by"] = h.get("stalled_by",
                                                "serving:" + eng.name)
                if serving:
                    h["serving"] = serving
            except Exception:
                pass
        if self.aggregator is not None:
            # supervisor view: the fleet's digests carry the liveness
            try:
                self.aggregator.collect()
                hosts = {}
                now = time.time()
                for r, d in self.aggregator.last.items():
                    hosts[d.get("host", f"rank-{r}")] = {
                        "step": d.get("step"),
                        "age_s": round(max(0.0, now - d.get("ts", now)), 3)}
                h["fleet"] = hosts
                if h["status"] == "starting" and hosts:
                    ages = [v["age_s"] for v in hosts.values()]
                    stall = h["stall_after_s"]
                    h["status"] = "stalled" if min(ages) > stall \
                        else "healthy"
            except Exception:
                pass
        return h

    # -- lifecycle -----------------------------------------------------------
    def start(self, port: int = 0, host: str = "") -> int:
        """Bind and serve on a daemon thread; returns the bound port."""
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # keep training stdout clean
                pass

            def _send(self, code: int, body: str, ctype: str,
                      headers: Optional[dict] = None):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                url = urlparse(self.path)
                try:
                    if url.path == "/metrics":
                        self._send(200, srv.metrics_text(),
                                   "text/plain; version=0.0.4")
                    elif url.path == "/snapshot":
                        self._send(200, json.dumps(srv.snapshot()),
                                   "application/json")
                    elif url.path == "/healthz":
                        h = srv.healthz()
                        self._send(200 if h["status"] != "stalled" else 503,
                                   json.dumps(h), "application/json")
                    elif url.path == "/events":
                        q = parse_qs(url.query)
                        try:
                            n = int(q.get("n", ["100"])[0])
                        except ValueError:
                            self._send(400, json.dumps(
                                {"error": f"n={q.get('n')[0]!r} must be "
                                          f"an integer"}),
                                "application/json")
                            return
                        kind = q.get("kind", [None])[0]
                        self._send(200, json.dumps(
                            {"events": _events_mod.recent(n, kind=kind)}),
                            "application/json")
                    elif url.path == "/profile":
                        code, payload = srv.profile(parse_qs(url.query))
                        self._send(code, json.dumps(payload),
                                   "application/json")
                    elif url.path == "/controller":
                        code, payload = srv.controller_status()
                        self._send(code, json.dumps(payload),
                                   "application/json")
                    elif url.path == "/requests":
                        code, payload = srv.requests_payload(
                            parse_qs(url.query))
                        self._send(code, json.dumps(payload),
                                   "application/json")
                    elif url.path == "/slo":
                        code, payload = srv.slo_payload(parse_qs(url.query))
                        self._send(code, json.dumps(payload),
                                   "application/json")
                    elif url.path == "/generate":
                        self._send(405, json.dumps(
                            {"error": "POST a JSON body "
                                      "{\"prompt\": [token ids], ...} "
                                      "to /generate"}),
                            "application/json")
                    else:
                        self._send(404, json.dumps(
                            {"error": "unknown path", "endpoints":
                             ["/metrics", "/snapshot", "/healthz",
                              "/events", "/profile", "/controller",
                              "/requests", "/slo", "/generate"]}),
                            "application/json")
                except BrokenPipeError:
                    pass
                except Exception as e:  # a handler bug must not kill a scrape
                    try:
                        self._send(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}),
                            "application/json")
                    except Exception:
                        pass

            def do_POST(self):
                url = urlparse(self.path)
                try:
                    if url.path == "/generate":
                        try:
                            length = int(self.headers.get(
                                "Content-Length", "0"))
                        except ValueError:
                            length = 0
                        body = self.rfile.read(length) if length else b""
                        code, payload = srv.generate_payload(body)
                        hdrs = None
                        if code == 503 and isinstance(payload, dict) and \
                                payload.get("retry_after_s") is not None:
                            hdrs = {"Retry-After": int(round(
                                float(payload["retry_after_s"])))}
                        self._send(code, json.dumps(payload),
                                   "application/json", headers=hdrs)
                    else:
                        self._send(404, json.dumps(
                            {"error": "unknown path", "endpoints":
                             ["/generate"]}), "application/json")
                except BrokenPipeError:
                    pass
                except Exception as e:  # a handler bug must not kill serving
                    try:
                        self._send(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}),
                            "application/json")
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=f"obs-server:{self.port}")
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._thread = None


def get_server() -> Optional[ObservabilityServer]:
    return _server


def stop_server():
    global _server
    if _server is not None:
        _server.stop()
        _server = None


def maybe_start_server(role: str = "trainer",
                       aggregator=None) -> Optional[ObservabilityServer]:
    """Start the process-wide server if `PADDLE_TPU_METRICS_PORT` is set
    (idempotent; returns the existing server on repeat calls).

    role="trainer" (Model.fit, bench.py): binds the configured port, wires
    a FleetReporter on every rank of a >=2 fleet and a FleetAggregator on
    rank 0 (both from the trainer env contract). role="supervisor"
    (tools/elastic_run.py): binds `PADDLE_TPU_SUPERVISOR_METRICS_PORT`
    (default configured port + 1 — the trainer child owns the configured
    one on this host); the supervisor passes its `aggregator` explicitly
    (built from --master) since it runs OUTSIDE the trainer env contract."""
    global _server, _reporter
    if _server is not None:
        return _server
    raw = os.environ.get("PADDLE_TPU_METRICS_PORT", "")
    if raw == "":
        return None
    try:
        port = int(raw)
    except ValueError:
        warnings.warn(f"PADDLE_TPU_METRICS_PORT={raw!r} is not a port "
                      f"number; observability server disabled")
        return None
    if role == "supervisor":
        # default: trainer child owns `port` on the same host, supervisor
        # takes port+1; a garbled override warns and keeps that default
        from ..utils.envparse import env_int
        port = env_int("PADDLE_TPU_SUPERVISOR_METRICS_PORT",
                       port + 1 if port else 0)
    elif aggregator is None:
        try:
            from ..distributed.fleet import telemetry as _telemetry
            aggregator = _telemetry.aggregator_from_env()
            if _reporter is None:
                _reporter = _telemetry.reporter_from_env()
        except Exception as e:
            warnings.warn(f"fleet telemetry unavailable ({e}); serving "
                          f"process-local metrics only")
    if aggregator is not None:
        try:
            # opt-in background collect loop (PADDLE_TPU_FLEET_POLL_SEC):
            # straggler/health detection without an external scraper
            aggregator.start_polling()
        except Exception:
            pass
    server = ObservabilityServer(aggregator=aggregator)
    try:
        bound = server.start(port)
    except OSError as e:
        warnings.warn(f"observability server could not bind port {port}: "
                      f"{e}; disabled for this process")
        return None
    _server = server
    import logging
    logging.getLogger("paddle_tpu.observability").info(
        "observability server (%s) on :%d — /metrics /snapshot /healthz "
        "/events", role, bound)
    return server
