"""Profiler with step-window scheduling and chrome-trace export.

Reference parity: `python/paddle/profiler/profiler.py` — `Profiler:262`
(start/stop/step/export/summary, context manager), `make_scheduler:65`
(closed→ready→record windows with repeat/skip_first),
`export_chrome_tracing:152` / `export_protobuf:203` (on_trace_ready
callables). Device-side: when a TPU target is profiled and `trace_dir` is
set, wraps `jax.profiler.start_trace/stop_trace` (XPlane → TensorBoard), the
TPU replacement for the reference's CUPTI CudaTracer.
"""
from __future__ import annotations

import json
import os
import socket
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

from .recorder import get_recorder
from .statistic import SortedKeys, StatisticData, summary_report


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1      # accepted for API parity; maps to the TPU device tracer
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Step-window state machine (reference `profiler.py:65`)."""
    num_steps = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        assert step >= 0
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        period = step // num_steps
        if repeat and period >= repeat:
            return ProfilerState.CLOSED
        pos = step % num_steps
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos < num_steps - 1:
            return ProfilerState.RECORD
        return ProfilerState.RECORD_AND_RETURN
    return scheduler


def _default_state_scheduler(step: int):
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str,
                          worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready callable writing chrome://tracing JSON
    (reference `profiler.py:152`)."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof: "Profiler"):
        name = worker_name or f"host_{socket.gethostname()}_pid_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{time.time_ns()}"
                                      f".paddle_trace.json")
        prof.export(path, format="json")
        return path
    return handler


def export_protobuf(dir_name: str,
                    worker_name: Optional[str] = None) -> Callable:
    """Parity alias — exports the same JSON payload with .pb.json suffix (we
    have no profiler.proto; the chrome JSON is the interchange format)."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof: "Profiler"):
        name = worker_name or f"host_{socket.gethostname()}_pid_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{time.time_ns()}"
                                      f".pb.json")
        prof.export(path, format="json")
        return path
    return handler


def _get_supported_targets() -> Iterable[ProfilerTarget]:
    targets = [ProfilerTarget.CPU]
    try:
        if any(d.platform == "tpu" for d in jax.devices()):
            targets.append(ProfilerTarget.TPU)
    except Exception:
        pass
    return targets


class Profiler:
    """Reference `profiler.py:262`.

    Usage:
        with Profiler(scheduler=(2, 5)) as p:
            for batch in loader:
                train_step(batch)
                p.step()
        p.summary()
    """

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, trace_dir: Optional[str] = None):
        self.targets = list(targets) if targets is not None \
            else list(_get_supported_targets())
        if scheduler is None:
            self._scheduler = _default_state_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(closed=max(start - 1, 0),
                                             ready=min(start, 1),
                                             record=end - start, repeat=1)
        else:
            self._scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.trace_dir = trace_dir
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._spans = []
        self._device_tracing = False
        self.xplane_stats = None  # correlation stats of the last window
        from .timer import benchmark
        self._benchmark = benchmark()

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._benchmark.begin()
        if self.timer_only:
            return
        self.current_state = self._scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._start_record()

    def stop(self):
        self._benchmark.end()
        if self.timer_only:
            return
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        self._benchmark.step(num_samples)
        if self.timer_only:
            self.step_num += 1
            return
        prev = self.current_state
        self.step_num += 1
        self.current_state = self._scheduler(self.step_num)
        recording = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        # RECORD_AND_RETURN always closes its window (even into a back-to-back
        # next window), so every window's trace is exported
        if prev == ProfilerState.RECORD_AND_RETURN:
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)
            if self.current_state in recording:
                self._start_record()
        elif prev not in recording and self.current_state in recording:
            self._start_record()
        elif prev in recording and self.current_state not in recording:
            self._stop_record()

    def step_info(self, unit: str = "samples") -> str:
        return self._benchmark.step_info(unit)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- recording ----------------------------------------------------------
    def _start_record(self):
        rec = get_recorder()
        rec.clear()
        rec.enabled = True
        if self.trace_dir and any(t in (ProfilerTarget.TPU, ProfilerTarget.GPU)
                                  for t in self.targets):
            try:
                jax.profiler.start_trace(self.trace_dir)
                self._device_tracing = True
                # per-op dispatch annotates the trace while it records, so
                # the correlation below can hand device time back per span
                from . import xplane as _xplane
                _xplane._ANNOTATING = True
            except Exception:
                self._device_tracing = False

    def _stop_record(self):
        rec = get_recorder()
        rec.enabled = False
        self._spans = rec.collect()
        if self._device_tracing:
            from . import xplane as _xplane
            _xplane._ANNOTATING = False
            try:
                jax.profiler.stop_trace()
            finally:
                self._device_tracing = False
            # upgrade span device time from the trace just written
            # (device_src="xplane" in summary/export); best-effort — the
            # roofline estimates survive when the parse finds nothing
            try:
                path = _xplane.find_trace_file(self.trace_dir)
                if path:
                    doc = _xplane.load_trace(path)
                    self.xplane_stats = _xplane.correlate(
                        self._spans, doc.get("traceEvents", []))
            except Exception:
                self.xplane_stats = None

    # -- results ------------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """Write collected host spans as chrome://tracing JSON."""
        events = []
        pid = os.getpid()
        for s in self._spans:
            args = dict(s.args) if s.args else {}
            if s.parent:
                args["parent"] = s.parent
            if s.device_ns is not None:
                # host dispatch vs device execution split (device_time.py);
                # src says whether it was measured (sync mode) or a
                # roofline estimate
                args["device_us"] = s.device_ns / 1e3
                args["device_src"] = s.device_src
            events.append({
                "name": s.name, "ph": "X", "cat": s.event_type,
                "ts": s.start_ns / 1e3, "dur": s.dur_ns / 1e3,
                "pid": pid, "tid": s.tid,
                "args": args,
            })
        payload = {"traceEvents": events,
                   "displayTimeUnit": "ms",
                   "metadata": {"producer": "paddle_tpu.profiler"}}
        with open(path, "w") as f:
            json.dump(payload, f)
        return path

    def statistic_data(self) -> StatisticData:
        return StatisticData(self._spans)

    def summary(self, sorted_by: SortedKeys = None, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = 'ms') -> str:
        report = summary_report(self.statistic_data(),
                                sorted_by=sorted_by, time_unit=time_unit)
        print(report)
        return report
