"""Per-op device-time attribution for host spans.

PR 2's op spans measure HOST dispatch latency (enqueue, not execution) —
on TPU the async dispatch returns in microseconds while the op runs on the
chip for milliseconds, so host spans alone cannot separate "dispatch-bound"
from "device-bound". Two attribution modes, recorded alongside each span:

* ``estimate`` (default, works everywhere incl. CPU CI): a roofline bound
  from the cost model — max(flops / peak_flops, bytes / peak_hbm_bw) for
  the span's op. Clearly labeled an ESTIMATE: cost-analysis numbers are
  cache-oblivious upper bounds, the same provenance bench.py already
  documents for hbm_gb_per_step.
* ``measured`` (`PADDLE_TPU_DEVICE_TIME=sync`): block_until_ready after
  each traced op, so the span's device time is the wall until device
  completion. This SERIALIZES the async dispatch pipeline — a profiling
  mode, never the default (the reference pays the same price for
  `nvprof --sync`-style tracing).

The full-fidelity third mode lives in `profiler/xplane.py`: a bounded
`jax.profiler` capture session whose parsed trace is correlated back onto
host spans (`device_src="xplane"`), replacing the estimate with measured
backend execution time wherever the correlation lands.

Peaks: TPU `BENCH_PEAK_FLOPS` (default 197e12, v5e bf16) and
`PADDLE_TPU_PEAK_HBM_GBS` (GB/s, default 819 = v5e); CPU gets deliberately
conservative defaults (100 GFLOP/s, 20 GB/s) so estimate rows stay
obviously synthetic there.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

__all__ = ["sync_mode", "estimate_ns", "attribute", "split_rows",
           "platform_peaks", "reset_peaks"]

_CPU_PEAK_FLOPS = 100e9
_CPU_PEAK_BW = 20e9

# cache keyed on the env knobs that feed it — a test or bench changing
# BENCH_PEAK_FLOPS / PADDLE_TPU_PEAK_HBM_GBS mid-process must see fresh
# peaks, not the first call's (the platform probe alone stays cached: a
# process cannot change backends)
_peaks_cache: Optional[Tuple[Tuple[Optional[str], Optional[str]],
                             Tuple[str, float, float]]] = None


def _platform() -> str:
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def platform_peaks() -> Tuple[str, float, float]:
    """(platform, peak_flops/s, peak_bytes/s) used by the estimator."""
    global _peaks_cache
    env_key = (os.environ.get("BENCH_PEAK_FLOPS"),
               os.environ.get("PADDLE_TPU_PEAK_HBM_GBS"))
    if _peaks_cache is not None and _peaks_cache[0] == env_key:
        return _peaks_cache[1]
    plat = _platform() if _peaks_cache is None else _peaks_cache[1][0]
    if plat == "cpu":
        peaks = (plat, _CPU_PEAK_FLOPS, _CPU_PEAK_BW)
    else:
        from ..utils.envparse import env_float
        flops = float(env_key[0]) if env_key[0] else 197e12
        bw = env_float("PADDLE_TPU_PEAK_HBM_GBS", 819.0) * 1e9
        peaks = (plat, flops, bw)
    _peaks_cache = (env_key, peaks)
    return peaks


def reset_peaks():
    """Drop the cached peaks (including the platform probe) — tests that
    monkeypatch the backend need this; env-knob changes are picked up
    automatically."""
    global _peaks_cache
    _peaks_cache = None


def sync_mode() -> bool:
    """True when PADDLE_TPU_DEVICE_TIME=sync: measure completion instead of
    estimating (serializes dispatch — profiling runs only)."""
    return os.environ.get("PADDLE_TPU_DEVICE_TIME", "").lower() == "sync"


def estimate_ns(flops: float, nbytes: float) -> int:
    """Roofline device-time estimate in ns: the op is bound by compute or
    memory, whichever is slower at the platform's peaks."""
    _, peak_flops, peak_bw = platform_peaks()
    sec = max((flops or 0.0) / peak_flops, (nbytes or 0.0) / peak_bw)
    return int(sec * 1e9)


def attribute(outs, flops: float, nbytes: float,
              start_ns: int) -> Tuple[int, str]:
    """(device_ns, source) for one traced op. In sync mode, waits for the
    op's outputs and reports wall-until-completion as "measured"; otherwise
    returns the roofline "estimate"."""
    if sync_mode():
        try:
            import jax
            from .recorder import now_ns
            jax.block_until_ready(outs)
            return max(0, now_ns() - start_ns), "measured"
        except Exception:
            pass  # fall through to the estimate
    return estimate_ns(flops, nbytes), "estimate"


#: provenance ranking: a row's src label is its best span's source
#: (xplane = correlated from a real jax.profiler trace, the authoritative
#: mode; measured = sync-mode wall; estimate = roofline bound)
SRC_PRIORITY = {"estimate": 0, "measured": 1, "xplane": 2}


def split_rows(spans) -> List[dict]:
    """Aggregate host-vs-device time per op name from spans that carry
    device attribution — the bench JSON's `device_time.rows` shape,
    sorted by device time desc."""
    acc: Dict[str, dict] = {}
    for s in spans:
        if getattr(s, "device_ns", None) is None:
            continue
        row = acc.setdefault(s.name, {"op": s.name, "calls": 0,
                                      "host_ms": 0.0, "device_ms": 0.0,
                                      "src": s.device_src or "estimate"})
        row["calls"] += 1
        row["host_ms"] += s.dur_ns / 1e6
        row["device_ms"] += s.device_ns / 1e6
        if SRC_PRIORITY.get(s.device_src, 0) > SRC_PRIORITY.get(row["src"], 0):
            row["src"] = s.device_src
    rows = sorted(acc.values(), key=lambda r: -r["device_ms"])
    for r in rows:
        r["host_ms"] = round(r["host_ms"], 4)
        r["device_ms"] = round(r["device_ms"], 4)
    return rows
