"""Concrete optimizers.

Reference kernels: `/root/reference/paddle/fluid/operators/optimizers/`
(sgd_op, momentum_op, adam_op, adamw_op, lamb_op, adagrad_op, rmsprop_op,
adadelta_op, adamax_op, lars_momentum_op). Updates are fp32 master-math on
arrays; XLA fuses the whole per-tree update (merged_adam equivalent).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class SGD(Optimizer):
    _fusable = True  # p - lr*g is elementwise
    def _update(self, p, g, slots, lr, t, **kw):
        g = self._decay_grad(p, g)
        return p - lr * g, slots


class Momentum(Optimizer):
    _fusable = True
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, p):
        return {"velocity": jnp.zeros_like(p.data, jnp.float32)}

    def _update(self, p, g, slots, lr, t, **kw):
        g = self._decay_grad(p, g)
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    _fusable = True  # AdamW inherits this
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p.data, jnp.float32),
                "moment2": jnp.zeros_like(p.data, jnp.float32)}

    def _update(self, p, g, slots, lr, t, **kw):
        g = self._decay_grad(p, g)
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return new_p, {"moment1": m, "moment2": v}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip)
        self._wd = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else float(getattr(weight_decay, "_coeff", 0.01))
        self._apply_decay_param_fun = apply_decay_param_fun

    def _param_kw(self, name):
        if self._apply_decay_param_fun is not None:
            return {"decay": bool(self._apply_decay_param_fun(name))}
        return {}

    def _update(self, p, g, slots, lr, t, decay=True, **kw):
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        # decoupled weight decay, skipped for excluded params
        wd = self._wd if decay else 0.0
        new_p = p * (1 - lr * wd) - lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return new_p, {"moment1": m, "moment2": v}


class Adamax(Optimizer):
    _fusable = True
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_slots(self, p):
        return {"moment": jnp.zeros_like(p.data, jnp.float32),
                "inf_norm": jnp.zeros_like(p.data, jnp.float32)}

    def _update(self, p, g, slots, lr, t, **kw):
        g = self._decay_grad(p, g)
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g))
        new_p = p - (lr / (1 - self._beta1 ** t)) * m / (u + self._eps)
        return new_p, {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    _fusable = True
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slots(self, p):
        return {"moment": jnp.full_like(p.data, self._init_acc, jnp.float32)}

    def _update(self, p, g, slots, lr, t, **kw):
        g = self._decay_grad(p, g)
        acc = slots["moment"] + g * g
        new_p = p - lr * g / (jnp.sqrt(acc) + self._eps)
        return new_p, {"moment": acc}


class RMSProp(Optimizer):
    _fusable = True
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, p):
        s = {"mean_square": jnp.zeros_like(p.data, jnp.float32),
             "momentum": jnp.zeros_like(p.data, jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p.data, jnp.float32)
        return s

    def _update(self, p, g, slots, lr, t, **kw):
        g = self._decay_grad(p, g)
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * slots["momentum"] + lr * g / denom
        new_p = p - mom
        out = {"mean_square": ms, "momentum": mom}
        if mg is not None:
            out["mean_grad"] = mg
        return new_p, out


class Adadelta(Optimizer):
    _fusable = True
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon

    def _init_slots(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p.data, jnp.float32),
                "avg_squared_update": jnp.zeros_like(p.data, jnp.float32)}

    def _update(self, p, g, slots, lr, t, **kw):
        g = self._decay_grad(p, g)
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = g * jnp.sqrt(slots["avg_squared_update"] + self._eps) / \
            jnp.sqrt(asg + self._eps)
        asu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * upd * upd
        return p - lr * upd, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Lamb(Optimizer):
    _fusable = False  # per-param trust-ratio norms
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _param_kw(self, name):
        if self._exclude_fn is not None:
            return {"decay": not bool(self._exclude_fn(name))}
        return {}

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p.data, jnp.float32),
                "moment2": jnp.zeros_like(p.data, jnp.float32)}

    def _update(self, p, g, slots, lr, t, decay=True, **kw):
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + (self._wd if decay else 0.0) * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"moment1": m, "moment2": v}


class LarsMomentum(Momentum):
    _fusable = False  # per-param LARS local lr
    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=1e-9, name=None):
        super().__init__(learning_rate, momentum, parameters, False, None, grad_clip)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon

    def _update(self, p, g, slots, lr, t, **kw):
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm /
            (g_norm + self._lars_wd * w_norm + self._eps), 1.0)
        g_eff = g + self._lars_wd * p
        v = self._momentum * slots["velocity"] + lr * local_lr * g_eff
        return p - v, {"velocity": v}
