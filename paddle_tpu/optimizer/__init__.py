"""paddle_tpu.optimizer — optimizers + lr schedulers.

Reference parity: `python/paddle/optimizer/`.
"""
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, LarsMomentum, Momentum,
    RMSProp,
)
from . import lr  # noqa: F401
