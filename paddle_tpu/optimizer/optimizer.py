"""Optimizer base.

Reference: `python/paddle/optimizer/optimizer.py:50` + the device optimizer
kernels (`/root/reference/paddle/fluid/operators/optimizers/`). Each
optimizer defines a pure per-parameter update `_update(p, g, slots, lr, t)`;
the eager `step()` walks parameters, while `apply_fn()` exposes the same
update as a jit-compatible pytree transform (the TPU equivalent of the
reference's fused `merged_adam` multi-tensor kernels — XLA fuses the whole
tree update into a couple of kernels).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..framework.param import Parameter
from .lr import LRScheduler


class Optimizer:
    # True on subclasses whose `_update` is purely ELEMENTWISE in the
    # parameter (every output element depends only on the same element of
    # p/g/slots plus scalars): such updates are value-identical on a
    # concatenated flat vector, which is what makes the fused multi-tensor
    # apply (`apply_fn(fused=True)`) bit-exact. Optimizers with per-param
    # reductions (Lamb trust ratio, LARS local lr) must keep this False.
    _fusable = False

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        if self._parameter_list is None:
            from ..static import in_static_mode
            if not in_static_mode():
                raise ValueError("parameters is required in dygraph mode")
            self._parameter_list = []
        self._grad_clip = grad_clip
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:  # L2Decay object
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay, "coeff", 0.0)))
        self._slots: Dict[int, dict] = {}
        self._step_count = 0

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate.get_lr())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    # -- per-parameter slots -------------------------------------------------
    def _init_slots(self, p: Parameter) -> dict:
        return {}

    def _update(self, p: jax.Array, g: jax.Array, slots: dict, lr, t: int, **kw):
        raise NotImplementedError

    def _param_kw(self, name: str) -> dict:
        """Per-parameter static update options (e.g. decay exclusion), keyed
        by parameter name. Overridden by AdamW/Lamb."""
        return {}

    def _decay_grad(self, p, g):
        """L2 regularization folded into the gradient (non-decoupled).
        No truthiness test on the coefficient: under the jitted update it is
        a TRACED scalar (so mutating `_weight_decay` mid-run takes effect,
        including 0 -> nonzero), and XLA folds the wd=0 multiply away."""
        wd = self._weight_decay
        if isinstance(wd, (int, float)) and not wd:
            return g
        return g + wd * p

    # -- eager step ----------------------------------------------------------
    @property
    def _param_groups(self):
        return self._parameter_list

    def _hyper_names(self):
        """Mutable float hyperparameters (`_weight_decay`, betas, rho, ...)
        threaded into the jitted update as TRACED arguments like `lr`/`t`,
        so mutating them mid-run takes effect instead of being silently
        baked in at first trace. Floats only: bools/ints steer static
        control flow and shapes. `_learning_rate` already rides as `lr`."""
        names = self.__dict__.get("_hyper_name_cache")
        if names is None:
            names = tuple(sorted(
                n for n, v in self.__dict__.items()
                if isinstance(v, float) and not isinstance(v, bool)
                and n != "_learning_rate"))
            self.__dict__["_hyper_name_cache"] = names
        return names

    def _get_jit_update(self, kw_key):
        """One jitted per-parameter update per static-kw combination; jit's
        own cache then keys on (shape, dtype). The eager loop previously
        dispatched each jnp op of `_update` individually (~10 dispatches x
        n_params per step — the analog of the reference replacing per-tensor
        adam with fused `merged_adam`, operators/optimizers/merged_adam_op)."""
        cache = self.__dict__.setdefault("_jit_updates", {})
        fn = cache.get(kw_key)
        if fn is None:
            kw = dict(kw_key)
            names = self._hyper_names()

            def u(p, g, slots, lr, t, hypers, _kw=kw, _names=names):
                # rebind the hyper attrs to the traced scalars for the
                # duration of the trace: subclass `_update` bodies read
                # `self._beta1` etc. unchanged, yet the compiled executable
                # takes the CURRENT values as runtime inputs every step
                saved = {n: getattr(self, n) for n in _names}
                try:
                    for n, v in zip(_names, hypers):
                        setattr(self, n, v)
                    return self._update(p, g, slots, lr, t, **_kw)
                finally:
                    for n, v in saved.items():
                        setattr(self, n, v)

            fn = jax.jit(u)
            cache[kw_key] = fn
        return fn

    def _hyper_values(self):
        return tuple(jnp.float32(getattr(self, n))
                     for n in self._hyper_names())

    def step(self):
        self._step_count += 1
        lr = self.get_lr()
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        # lr/t/hypers as device scalars: traced args, so a scheduler tick,
        # step increment, or hyperparameter mutation never recompiles the
        # update (hypers hoisted out of the loop — identical within a step)
        lr_a = jnp.float32(lr)
        t_a = jnp.int32(self._step_count)
        hyper_vals = self._hyper_values()
        for p, g in params_grads:
            if g is None:
                continue
            sid = id(p)
            if sid not in self._slots:
                self._slots[sid] = self._init_slots(p)
            g_arr = g.data.astype(jnp.float32) if g.data.dtype != p.data.dtype \
                else g.data
            kw = self._param_kw(p.name or "")
            if self.__dict__.get("_jit_step_broken"):
                new_p, new_slots = self._update(p.data, g_arr,
                                                self._slots[sid],
                                                lr, self._step_count, **kw)
            else:
                try:
                    upd = self._get_jit_update(tuple(sorted(kw.items())))
                    new_p, new_slots = upd(p.data, g_arr, self._slots[sid],
                                           lr_a, t_a, hyper_vals)
                except Exception:
                    # a subclass _update that can't trace (host callbacks,
                    # data-dependent python control flow) falls back to the
                    # eager composition permanently for this instance
                    self._jit_step_broken = True
                    new_p, new_slots = self._update(p.data, g_arr,
                                                    self._slots[sid],
                                                    lr, self._step_count,
                                                    **kw)
            p.data = new_p.astype(p.data.dtype)
            self._slots[sid] = new_slots

    # paddle legacy API
    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static import Variable, append_backward
        if isinstance(loss, Variable):
            # static mode: attach this optimizer to the program; Executor
            # compiles fwd+bwd+update into one XLA executable
            pairs = append_backward(loss, parameters)
            loss._prog.optimizer = self
            loss._prog.version += 1
            return [], pairs
        loss.backward()
        self.step()
        self.clear_grad()
        return [], []

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    # -- functional interface (for compiled training steps) ------------------
    def init_state_tree(self, params_tree):
        """Build the slot pytree for a params pytree of jax arrays."""
        def mk(p):
            fake = Parameter(p)
            return self._init_slots(fake)
        return jax.tree_util.tree_map(mk, params_tree)

    @property
    def fused_update_supported(self) -> bool:
        """May `apply_fn(fused=True)` group this optimizer's update?"""
        return bool(type(self)._fusable)

    def apply_fn(self, params_tree, grads_tree, state_tree, lr=None, t=1,
                 fused=False):
        """Pure update: (params, grads, slots) -> (new_params, new_slots).

        ``fused=True`` (elementwise optimizers only, see ``_fusable``)
        runs ONE ``_update`` per (dtype, static-kw, slot-layout) group
        over flattened+concatenated leaves — the merged_adam /
        multi-tensor-apply form (reference
        operators/optimizers/merged_adam_op): instead of ~n_params small
        per-parameter fusions the compiled step gets a handful of big
        ones, shrinking the optimizer segment's launch overhead.
        Elementwise math on a concatenated vector is BIT-IDENTICAL per
        element to the per-parameter loop (pinned by
        tests/test_fused_opt.py), so the two paths are interchangeable
        mid-run. Callers with per-leaf sharded state (ZeRO) should keep
        the default: concatenation would force cross-shard gathers.
        """
        lr = self.get_lr() if lr is None else lr
        if self._grad_clip is not None and hasattr(self._grad_clip, "clip_fn"):
            grads_tree = self._grad_clip.clip_fn(grads_tree)
        flat_kp, treedef = jax.tree_util.tree_flatten_with_path(params_tree)
        names = [jax.tree_util.keystr(kp) for kp, _ in flat_kp]
        flat_p = [p for _, p in flat_kp]
        flat_g = jax.tree_util.tree_flatten(grads_tree)[0]
        flat_s = treedef.flatten_up_to(state_tree)
        if fused and self.fused_update_supported and len(flat_p) > 1:
            new_p, new_s = self._apply_fused(names, flat_p, flat_g, flat_s,
                                             lr, t)
        else:
            new_p, new_s = [], []
            for name, p, g, s in zip(names, flat_p, flat_g, flat_s):
                np_, ns_ = self._update(
                    p, g.astype(jnp.float32) if g.dtype != p.dtype else g,
                    s, lr, t, **self._param_kw(name))
                new_p.append(np_.astype(p.dtype))
                new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s))

    def _apply_fused(self, names, flat_p, flat_g, flat_s, lr, t):
        """Grouped multi-tensor update (see apply_fn). A leaf only joins a
        group when every slot is an array of the param's shape (a loaded
        legacy state_dict could hold anything); odd leaves fall back to
        the per-parameter update within the same traced program."""
        flat_g = [g.astype(jnp.float32) if g.dtype != p.dtype else g
                  for p, g in zip(flat_p, flat_g)]
        groups: dict = {}
        for i, (name, p, g, s) in enumerate(zip(names, flat_p, flat_g,
                                                flat_s)):
            kw_key = tuple(sorted(self._param_kw(name).items()))
            slots_ok = all(
                hasattr(v, "shape") and tuple(v.shape) == tuple(p.shape)
                for v in s.values())
            key = (str(p.dtype), str(g.dtype), kw_key,
                   tuple(sorted((k, str(v.dtype)) for k, v in s.items()))) \
                if slots_ok else ("solo", i)
            groups.setdefault(key, []).append(i)
        new_p = [None] * len(flat_p)
        new_s = [None] * len(flat_p)
        for key, idxs in groups.items():
            if key[0] == "solo" or len(idxs) == 1:
                for i in idxs:
                    np_, ns_ = self._update(flat_p[i], flat_g[i], flat_s[i],
                                            lr, t,
                                            **self._param_kw(names[i]))
                    new_p[i] = np_.astype(flat_p[i].dtype)
                    new_s[i] = ns_
                continue
            kw = dict(key[2])
            sizes = [int(flat_p[i].size) for i in idxs]
            p_vec = jnp.concatenate([flat_p[i].reshape(-1) for i in idxs])
            g_vec = jnp.concatenate([flat_g[i].reshape(-1) for i in idxs])
            s_vec = {k: jnp.concatenate([flat_s[i][k].reshape(-1)
                                         for i in idxs])
                     for k in flat_s[idxs[0]]}
            np_vec, ns_vec = self._update(p_vec, g_vec, s_vec, lr, t, **kw)
            offs = np.cumsum(sizes)[:-1]
            p_parts = jnp.split(np_vec, offs)
            s_parts = {k: jnp.split(v, offs) for k, v in ns_vec.items()}
            for j, i in enumerate(idxs):
                shape = flat_p[i].shape
                new_p[i] = p_parts[j].reshape(shape).astype(flat_p[i].dtype)
                new_s[i] = {k: s_parts[k][j].reshape(shape)
                            for k in s_parts}
        return new_p, new_s

    # -- checkpointing -------------------------------------------------------
    def state_dict(self):
        sd = {"step": self._step_count}
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        for i, p in enumerate(self._parameter_list):
            slots = self._slots.get(id(p))
            if slots:
                key = p.name or f"param_{i}"
                for sname, sval in slots.items():
                    sd[f"{key}.{sname}"] = np.asarray(sval) if isinstance(sval, jax.Array) else sval
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("step", 0))
        if isinstance(self._learning_rate, LRScheduler) and "LR_Scheduler" in state_dict:
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list):
            key = p.name or f"param_{i}"
            slots = {}
            for sname_full, sval in state_dict.items():
                if sname_full.startswith(key + "."):
                    sname = sname_full[len(key) + 1:]
                    slots[sname] = jnp.asarray(sval) if isinstance(sval, np.ndarray) else sval
            if slots:
                self._slots[id(p)] = slots
