"""Discrete Fourier transforms.

Reference parity: `python/paddle/fft.py` (fft/ifft/rfft/irfft/hfft/ihfft +
2-D/N-D variants, fftfreq/rfftfreq, fftshift/ifftshift; C++ backend
`paddle/fluid/operators/spectral_op.*` pocketfft/cuFFT). TPU-native: jnp.fft
lowers to XLA's FFT HLO; eager autograd rides the op-dispatch tape
(`paddle_tpu.ops._dispatch.call` + jax.vjp), replacing the hand-written
spectral grad kernels. Hermitian N-D variants use the identity
``hfftn(x) = irfftn(conj(x), norm=swap(norm))`` (the same construction the
reference's fftn_c2r/forward=True kernel performs).
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops import _dispatch as _d
from .ops._dispatch import kernel


def _swap_norm(norm):
    if norm == "backward":
        return "forward"
    if norm == "forward":
        return "backward"
    if norm == "ortho":
        return "ortho"
    raise ValueError(f"invalid norm {norm!r}, expected backward/forward/ortho")


def _check_norm(norm):
    if norm not in ("backward", "forward", "ortho"):
        raise ValueError(f"invalid norm {norm!r}, expected backward/forward/ortho")
    return norm


def _op(opname, fn):
    impl = kernel(opname)(fn)
    def wrapper(*tensors, **attrs):
        return _d.call(impl, tensors, kwargs=attrs, name=opname)
    wrapper.__name__ = opname
    return wrapper


# 1-D ----------------------------------------------------------------------
_fft_impl = _op("fft_c2c", lambda x, n=None, axis=-1, norm="backward":
                jnp.fft.fft(x, n=n, axis=axis, norm=norm))
_ifft_impl = _op("ifft_c2c", lambda x, n=None, axis=-1, norm="backward":
                 jnp.fft.ifft(x, n=n, axis=axis, norm=norm))
_rfft_impl = _op("fft_r2c", lambda x, n=None, axis=-1, norm="backward":
                 jnp.fft.rfft(x, n=n, axis=axis, norm=norm))
_irfft_impl = _op("fft_c2r", lambda x, n=None, axis=-1, norm="backward":
                  jnp.fft.irfft(x, n=n, axis=axis, norm=norm))
_hfft_impl = _op("hfft", lambda x, n=None, axis=-1, norm="backward":
                 jnp.fft.irfft(jnp.conj(x), n=n, axis=axis, norm=_swap_norm(norm)))
_ihfft_impl = _op("ihfft", lambda x, n=None, axis=-1, norm="backward":
                  jnp.conj(jnp.fft.rfft(x, n=n, axis=axis, norm=_swap_norm(norm))))


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft_impl(x, n=n, axis=axis, norm=_check_norm(norm))


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _ifft_impl(x, n=n, axis=axis, norm=_check_norm(norm))


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _rfft_impl(x, n=n, axis=axis, norm=_check_norm(norm))


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _irfft_impl(x, n=n, axis=axis, norm=_check_norm(norm))


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _hfft_impl(x, n=n, axis=axis, norm=_check_norm(norm))


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _ihfft_impl(x, n=n, axis=axis, norm=_check_norm(norm))


# N-D ----------------------------------------------------------------------
_fftn_impl = _op("fftn_c2c", lambda x, s=None, axes=None, norm="backward":
                 jnp.fft.fftn(x, s=s, axes=axes, norm=norm))
_ifftn_impl = _op("ifftn_c2c", lambda x, s=None, axes=None, norm="backward":
                  jnp.fft.ifftn(x, s=s, axes=axes, norm=norm))
_rfftn_impl = _op("fftn_r2c", lambda x, s=None, axes=None, norm="backward":
                  jnp.fft.rfftn(x, s=s, axes=axes, norm=norm))
_irfftn_impl = _op("fftn_c2r", lambda x, s=None, axes=None, norm="backward":
                   jnp.fft.irfftn(x, s=s, axes=axes, norm=norm))
_hfftn_impl = _op("hfftn", lambda x, s=None, axes=None, norm="backward":
                  jnp.fft.irfftn(jnp.conj(x), s=s, axes=axes,
                                 norm=_swap_norm(norm)))
_ihfftn_impl = _op("ihfftn", lambda x, s=None, axes=None, norm="backward":
                   jnp.conj(jnp.fft.rfftn(x, s=s, axes=axes,
                                          norm=_swap_norm(norm))))


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _fftn_impl(x, s=s, axes=axes, norm=_check_norm(norm))


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _ifftn_impl(x, s=s, axes=axes, norm=_check_norm(norm))


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _rfftn_impl(x, s=s, axes=axes, norm=_check_norm(norm))


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _irfftn_impl(x, s=s, axes=axes, norm=_check_norm(norm))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hfftn_impl(x, s=s, axes=axes, norm=_check_norm(norm))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _ihfftn_impl(x, s=s, axes=axes, norm=_check_norm(norm))


# 2-D (thin aliases over N-D, like the reference) ---------------------------
def _check_2d(x, s, axes):
    if s is not None and len(s) != 2:
        raise ValueError("s must be length-2 for 2-D transforms")
    if axes is not None and len(axes) != 2:
        raise ValueError("axes must be length-2 for 2-D transforms")


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_2d(x, s, axes)
    return fftn(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_2d(x, s, axes)
    return ifftn(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_2d(x, s, axes)
    return rfftn(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_2d(x, s, axes)
    return irfftn(x, s=s, axes=axes, norm=norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_2d(x, s, axes)
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    _check_2d(x, s, axes)
    return ihfftn(x, s=s, axes=axes, norm=norm)


# helpers ------------------------------------------------------------------
def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.tensor import Tensor
    from .framework import dtype as dtype_mod
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        out = out.astype(dtype_mod.convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.tensor import Tensor
    from .framework import dtype as dtype_mod
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        out = out.astype(dtype_mod.convert_dtype(dtype))
    return Tensor(out)


_fftshift_impl = _op("fftshift", lambda x, axes=None: jnp.fft.fftshift(x, axes=axes))
_ifftshift_impl = _op("ifftshift", lambda x, axes=None: jnp.fft.ifftshift(x, axes=axes))


def fftshift(x, axes=None, name=None):
    if axes is not None and not isinstance(axes, (list, tuple)):
        axes = (int(axes),)
    return _fftshift_impl(x, axes=tuple(axes) if axes is not None else None)


def ifftshift(x, axes=None, name=None):
    if axes is not None and not isinstance(axes, (list, tuple)):
        axes = (int(axes),)
    return _ifftshift_impl(x, axes=tuple(axes) if axes is not None else None)


__all__ = [
    'fft', 'ifft', 'rfft', 'irfft', 'hfft', 'ihfft',
    'fft2', 'ifft2', 'rfft2', 'irfft2', 'hfft2', 'ihfft2',
    'fftn', 'ifftn', 'rfftn', 'irfftn', 'hfftn', 'ihfftn',
    'fftfreq', 'rfftfreq', 'fftshift', 'ifftshift',
]
