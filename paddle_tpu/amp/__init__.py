"""AMP — bf16-first automatic mixed precision.

Reference: `python/paddle/amp/` (`auto_cast.py:21`, `decorate:81`,
`grad_scaler.py:26`) and the C++ autocast hook
(`/root/reference/paddle/fluid/imperative/amp_auto_cast.h:44`). On TPU the
native fast dtype is bfloat16: loss scaling is a no-op by default (bf16 has
fp32's exponent range) but the `GradScaler` API is kept for parity, and does
real dynamic scaling when `dtype='float16'` is requested.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops._dispatch import amp_state
from ..profiler import metrics as _metrics_mod

_REG = _metrics_mod.default_registry()
_M_FOUND_INF = _REG.counter(
    "amp_found_inf_total",
    "GradScaler unscale passes that found nonfinite scaled gradients "
    "(each one skips the optimizer step and feeds the loss-scale backoff)")
_M_LOSS_SCALE = _REG.gauge(
    "amp_loss_scale",
    "current dynamic loss scale of the newest GradScaler — a collapsing "
    "value means gradients keep overflowing")


@jax.jit
def _unscale_and_check(grads, inv):
    """ONE fused program over every gradient leaf: unscale and reduce an
    all-leaves finite check. Replaces the per-gradient host sync loop
    (bool(~jnp.all(...)) per leaf) with a single device->host fetch of
    `bad` at the caller."""
    scaled = [g * inv for g in grads]
    bad = jnp.zeros((), jnp.bool_)
    for g in scaled:
        bad = bad | ~jnp.all(jnp.isfinite(g))
    return scaled, bad


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    st = amp_state()
    prev = dict(st)
    st["enabled"] = bool(enable)
    st["level"] = level
    st["dtype"] = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
    st["custom_white"] = set(custom_white_list or ())
    st["custom_black"] = set(custom_black_list or ())
    try:
        yield
    finally:
        st.update(prev)


amp_guard = auto_cast  # legacy alias


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model parameters to the amp dtype (master weights stay fp32
    inside the optimizer's slot math)."""
    amp_dtype = jnp.bfloat16 if dtype in ("bfloat16", "bf16") else jnp.float16
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if p.data.dtype == jnp.dtype(jnp.float32):
                    p.data = p.data.astype(amp_dtype)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (needed for fp16; pass-through for bf16).

    Reference: `python/paddle/amp/grad_scaler.py:26` +
    `check_finite_and_unscale` / `update_loss_scaling` ops.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False  # OptimizerState.UNSCALED equivalent
        if enable and _metrics_mod.enabled():
            _M_LOSS_SCALE.set(self._scale)

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        params = [p for p in optimizer._parameter_list
                  if p.grad is not None]
        if params:
            scaled, bad = _unscale_and_check(
                [p.grad.data for p in params], 1.0 / self._scale)
            found_inf = bool(bad)  # the one device sync of the pass
            for p, g in zip(params, scaled):
                p.grad = Tensor(g)
        else:
            found_inf = False
        self._found_inf = found_inf
        if found_inf and _metrics_mod.enabled():
            _M_FOUND_INF.inc()
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        # unscale happens against the already-populated grads
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        if _metrics_mod.enabled():
            # scale as a gauge: loss-scale collapse is visible on /metrics
            _M_LOSS_SCALE.set(self._scale)

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)
        if _metrics_mod.enabled():
            _M_LOSS_SCALE.set(self._scale)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)
        if self._enable and _metrics_mod.enabled():
            _M_LOSS_SCALE.set(self._scale)
