"""Deterministic fault injection at named sites.

Instrumented code declares a site — `fault.site("store.get")` — which is a
no-op until armed.  Arming happens either programmatically
(`fault.configure("store.get", times=1)`) or via the
`PADDLE_TPU_FAULT_SPEC` environment variable, which spawned DataLoader
worker processes inherit, so a single spec string can fault any layer of a
training job.

Spec grammar (semicolon-separated clauses)::

    spec   := clause (';' clause)*
    clause := site '=' count ['@' start] [':' kind]
    kind   := 'error' | 'timeout' | 'oserror' | 'kill' | 'delay'

`count` occurrences are faulted starting at the `start`-th call of the
site (1-based, default 1).  Occurrences are counted per process.  Examples:

    store.get=2                 fail the first two store.get calls
    ps.pull_dense=1@3           fail only the third pull_dense RPC
    dataloader.worker0=1:kill   worker 0 os._exit()s on its first batch
    fleet.step=100:delay        slow this host's steps (straggler chaos)

`delay` raises nothing: it sleeps `PADDLE_TPU_FAULT_DELAY` seconds
(default 0.05) at the site — the "slow host, not dead host" failure mode
the fleet straggler detector exists for.

Every injected fault increments `fault_injected_total{site=,kind=}` in the
metrics registry AND lands one `fault_injected` event in the unified event
log, so a chaos run's recovery story is auditable from the prometheus/JSON
snapshot alongside the retry counters.
"""
from __future__ import annotations

import os
import threading
import time
import warnings
from dataclasses import dataclass
from typing import Dict, Optional

from ..profiler import events as _events_mod
from ..profiler import metrics as _metrics_mod

SPEC_ENV = "PADDLE_TPU_FAULT_SPEC"

_REG = _metrics_mod.default_registry()
_M_INJECTED = _REG.counter(
    "fault_injected_total",
    "faults injected at instrumented sites, labeled by site and kind")


class InjectedFault(RuntimeError):
    """Raised by an armed fault site (kind=error)."""


class InjectedTimeout(TimeoutError):
    """Raised by an armed fault site (kind=timeout)."""


class InjectedIOError(OSError):
    """Raised by an armed fault site (kind=oserror)."""


class DeviceOOMError(RuntimeError):
    """Device memory exhausted (typed detection at the allocator boundary).

    Raised by the eager dispatch when XLA reports RESOURCE_EXHAUSTED / OOM
    for an op, or when the `device.alloc` fault site is armed — named so
    callers can catch the OOM specifically (shrink batch, flush caches)
    instead of pattern-matching XlaRuntimeError strings."""

    def __init__(self, op: str, bytes_estimate: int = 0, detail: str = ""):
        msg = f"device out of memory in op {op!r}"
        if bytes_estimate:
            msg += f" (~{bytes_estimate} bytes touched)"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.op = op
        self.bytes_estimate = int(bytes_estimate)


_KINDS = ("error", "timeout", "oserror", "kill", "delay")

#: Registry of every fault site the package declares — the single source
#: of truth the convention lint (analysis/conventions.py
#: lint_fault_sites) holds `site("...")` call sites against, mirrored by
#: the README "Fault sites" table. A site string used in code but absent
#: here (or registered here with no call site left — a dead site) fails
#: the lint in tier-1.
KNOWN_SITES = {
    "store.get": "TCPStore get (retry-wrapped)",
    "store.set": "TCPStore set (retry-wrapped)",
    "store.add": "TCPStore atomic add (retry-wrapped)",
    "store.check": "TCPStore key-presence check (retry-wrapped)",
    "parallel.init": "collective rendezvous in init_parallel_env",
    "collective.timeout": "eager collective launch (guarded deadline)",
    "device.alloc": "eager dispatch allocator boundary (OOM detection)",
    "ckpt.commit": "coordinated-checkpoint commit phase",
    "ckpt.chunk_write": "sharded-checkpoint chunk write",
    "ckpt.reshard": "sharded-checkpoint re-sharding restore",
    "heter.pull": "heter-PS sparse pull stage",
    "heter.push": "heter-PS sparse push stage",
    "fleet.step": "per-step fleet telemetry hook (straggler chaos)",
    "serving.decode": "per-iteration serving decode dispatch "
                      "(latency chaos for SLO breach drills)",
    "serving.swap": "checkpoint hot-swap load/stage path "
                    "(bad-push and torn-load drills)",
    "serving.wedge": "top of the serving step loop "
                     "(delay kind wedges the decode loop for "
                     "watchdog-restart drills)",
    "serving.admit": "request admission into the serving queue "
                     "(shed and admission-failure drills)",
    "controller.lease": "leader-lease renew write (drop renews to force "
                        "a standby takeover / failover drill)",
    "disagg.prefill": "prefill-worker forward pass (kill a worker "
                      "mid-prefill; the pipeline must requeue + respawn)",
}

#: dynamic site families: call sites build the name from a prefix +
#: runtime suffix (worker index, PS RPC op name)
DYNAMIC_SITES = {
    "dataloader.worker": "DataLoader worker <N> per-batch site (and the "
                         "bare generic site)",
    "ps.": "PS client RPC, by op (ps.pull_dense, ps.push_sparse, ...)",
}


@dataclass
class _Rule:
    count: int          # how many occurrences to fault
    start: int = 1      # 1-based first faulted occurrence
    kind: str = "error"
    fired: int = 0      # how many faults this rule has injected


def _parse_clause(clause: str) -> Optional[tuple]:
    site_name, sep, action = clause.partition("=")
    site_name = site_name.strip()
    if not sep or not site_name:
        return None
    action = action.strip()
    kind = "error"
    if ":" in action:
        action, kind = action.rsplit(":", 1)
        kind = kind.strip().lower()
        if kind not in _KINDS:
            return None
    start = 1
    if "@" in action:
        action, s = action.split("@", 1)
        start = int(s)
    count = int(action)
    if count < 0 or start < 1:
        return None
    return site_name, _Rule(count=count, start=start, kind=kind)


class FaultInjector:
    """Per-process registry of armed fault sites (thread-safe)."""

    def __init__(self, spec: Optional[str] = None):
        self._lock = threading.Lock()
        self._rules: Dict[str, _Rule] = {}
        self._seen: Dict[str, int] = {}
        if spec is None:
            spec = os.environ.get(SPEC_ENV, "")
        if spec:
            self.load_spec(spec)

    def load_spec(self, spec: str):
        """Parse and arm a spec string; malformed clauses warn, not crash —
        a typo in an env var must never take down a production job."""
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            try:
                parsed = _parse_clause(clause)
            except ValueError:
                parsed = None
            if parsed is None:
                warnings.warn(
                    f"{SPEC_ENV}: ignoring malformed clause {clause!r} "
                    f"(grammar: site=count[@start][:kind], kind in {_KINDS})")
                continue
            name, rule = parsed
            with self._lock:
                self._rules[name] = rule

    def configure(self, site: str, times: int = 1, start: int = 1,
                  kind: str = "error"):
        """Programmatic arming (tests): fault `times` occurrences of `site`
        starting at the `start`-th call."""
        if kind not in _KINDS:
            raise ValueError(f"kind must be one of {_KINDS}, got {kind!r}")
        with self._lock:
            self._rules[site] = _Rule(count=times, start=start, kind=kind)

    def reset(self):
        """Disarm every site and zero occurrence counters."""
        with self._lock:
            self._rules.clear()
            self._seen.clear()

    def fired(self, site: str) -> int:
        """How many faults have been injected at `site` in this process."""
        with self._lock:
            rule = self._rules.get(site)
            return rule.fired if rule else 0

    def site(self, name: str):
        """Declare one occurrence of a fault site; injects if armed."""
        if not self._rules:
            # lock-free fast path: sites now sit on per-op hot paths (the
            # eager dispatch's allocator boundary, collective entry points),
            # and an unarmed injector must cost one dict truthiness check.
            # Arming happens-before the faulted call in every supported use
            # (env spec at import, configure() before the exercised code).
            return
        with self._lock:
            if not self._rules:
                return
            rule = self._rules.get(name)
            if rule is None:
                return
            n = self._seen.get(name, 0) + 1
            self._seen[name] = n
            if not (rule.start <= n < rule.start + rule.count):
                return
            rule.fired += 1
            kind = rule.kind
        if _metrics_mod.enabled():
            _M_INJECTED.inc(site=name, kind=kind)
        _events_mod.emit("fault_injected", severity="warn",
                         site=name, fault_kind=kind)
        if kind == "kill":
            # simulate a preemption / OOM-kill of this process: no cleanup,
            # no exception propagation — the parent sees a corpse
            os._exit(17)
        if kind == "delay":
            # slow, not dead: the straggler failure mode — nothing raises,
            # including on a garbled PADDLE_TPU_FAULT_DELAY (delay is legal
            # at ANY site; a ValueError escaping here would crash the op
            # with an error unrelated to the slow-host semantics)
            raw = os.environ.get("PADDLE_TPU_FAULT_DELAY", "0.05")
            try:
                delay = float(raw)
            except ValueError:
                warnings.warn(f"PADDLE_TPU_FAULT_DELAY={raw!r} is not a "
                              f"number; using 0.05s")
                delay = 0.05
            time.sleep(delay)
            return
        if kind == "timeout":
            raise InjectedTimeout(f"injected timeout at fault site {name!r}")
        if kind == "oserror":
            raise InjectedIOError(f"injected I/O error at fault site {name!r}")
        raise InjectedFault(f"injected fault at site {name!r}")


_default = FaultInjector()


def default_injector() -> FaultInjector:
    return _default


def site(name: str):
    """Module-level shorthand: `fault.site("store.get")`."""
    _default.site(name)


def configure(site_name: str, times: int = 1, start: int = 1,
              kind: str = "error"):
    _default.configure(site_name, times=times, start=start, kind=kind)


def reset():
    _default.reset()


def reload_spec():
    """Re-read PADDLE_TPU_FAULT_SPEC (after reset) — lets tests arm faults
    by mutating os.environ mid-process."""
    _default.reset()
    spec = os.environ.get(SPEC_ENV, "")
    if spec:
        _default.load_spec(spec)
