"""paddle_tpu.fault — fault-tolerance primitives.

Two halves, used together across the runtime:

* `RetryPolicy` / `retry_call` / `retryable` — bounded exponential backoff
  with deterministic jitter and optional per-attempt timeout.  The TCPStore,
  PS client, and checkpoint manager all retry through this, and every retry
  lands in the metrics registry (`retry_attempts_total{op=...}`).
* `FaultInjector` / `site` — deterministic fault injection at named sites,
  armed by `PADDLE_TPU_FAULT_SPEC` or `fault.configure(...)`.  Injected
  faults are counted in `fault_injected_total{site=,kind=}`.

Together they make recovery *provable*: a chaos test arms a spec, runs
training, and asserts from the metrics snapshot that the faults fired and
were retried/recovered.
"""
from .inject import (  # noqa: F401
    SPEC_ENV, DeviceOOMError, FaultInjector, InjectedFault, InjectedIOError,
    InjectedTimeout, configure, default_injector, reload_spec, reset, site,
)
from .retry import (  # noqa: F401
    AttemptTimeout, RetryExhaustedError, RetryPolicy, retry_call, retryable,
)

__all__ = [
    "AttemptTimeout", "DeviceOOMError", "FaultInjector", "InjectedFault",
    "InjectedIOError", "InjectedTimeout", "RetryExhaustedError",
    "RetryPolicy", "SPEC_ENV", "configure", "default_injector",
    "reload_spec", "reset", "retry_call", "retryable", "site",
]
