"""Bounded retry with exponential backoff + deterministic jitter.

Reference: the reference PS stack retries at the brpc layer
(`brpc_ps_client.cc` FLAGS_pserver_timeout_ms / connect retries) and the
elastic manager re-registers etcd leases on transient failures.  Here one
policy object serves every distributed edge (TCPStore, PS RPC, checkpoint
I/O) so the knobs are uniform and every retry is visible in the metrics
registry (`retry_attempts_total{op=...}` / `retry_exhausted_total{op=...}`).

Jitter is drawn from a seeded PRNG private to the policy instance, so a
given policy replays the exact same backoff schedule run after run —
deterministic fault-injection tests stay deterministic end to end.
"""
from __future__ import annotations

import functools
import os
import time
from typing import Callable, Optional, Tuple, Type

from ..profiler import events as _events_mod
from ..profiler import metrics as _metrics_mod

_REG = _metrics_mod.default_registry()
_M_RETRIES = _REG.counter(
    "retry_attempts_total",
    "failed attempts that were retried, labeled by logical operation")
_M_EXHAUSTED = _REG.counter(
    "retry_exhausted_total",
    "operations that failed every attempt and gave up")
_M_RECOVERED = _REG.counter(
    "retry_recovered_total",
    "operations that succeeded after at least one retry")


class RetryExhaustedError(RuntimeError):
    """All attempts failed. Carries the op name and the last exception."""

    def __init__(self, op: str, attempts: int, last: BaseException):
        super().__init__(
            f"operation {op!r} failed after {attempts} attempt(s); "
            f"last error: {type(last).__name__}: {last}")
        self.op = op
        self.attempts = attempts
        self.last = last


class AttemptTimeout(TimeoutError):
    """A single attempt exceeded the policy's per-attempt timeout."""


class RetryPolicy:
    """Exponential backoff + jitter, per-attempt timeout, max attempts.

    delay(i) = min(max_delay, base_delay * 2**i) * (1 + jitter * u),
    u in [0, 1) from a PRNG seeded with `seed` — the schedule is
    reproducible for a given policy instance.

    `attempt_timeout` (seconds) bounds each attempt by running it on a
    worker thread; a timed-out attempt counts as a failure and is retried.
    The abandoned call keeps running on its thread until it returns — only
    use attempt_timeout with calls that are safe to abandon.
    """

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.05,
                 max_delay: float = 2.0, jitter: float = 0.25,
                 attempt_timeout: Optional[float] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 seed: int = 0):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.attempt_timeout = attempt_timeout
        self.retry_on = retry_on
        self.seed = int(seed)
        import random
        self._rng = random.Random(self.seed)

    @classmethod
    def from_env(cls, prefix: str, **defaults) -> "RetryPolicy":
        """Build a policy from PADDLE_TPU_<PREFIX>_{RETRIES,BACKOFF,TIMEOUT}
        env knobs, falling back to `defaults` then class defaults."""
        from ..utils import envparse
        env = os.environ
        p = f"PADDLE_TPU_{prefix.upper()}_"
        # garbled knob values warn + keep the caller's default (shared
        # envparse contract) — a typo'd PADDLE_TPU_STORE_RETRIES must not
        # detonate as an anonymous ValueError at TCPStore construction
        if p + "RETRIES" in env:
            defaults["max_attempts"] = envparse.env_int(
                p + "RETRIES", defaults.get("max_attempts", 3))
        if p + "BACKOFF" in env:
            defaults["base_delay"] = envparse.env_float(
                p + "BACKOFF", defaults.get("base_delay", 0.05))
        if p + "TIMEOUT" in env:
            t = envparse.env_float(p + "TIMEOUT", 0.0)
            defaults["attempt_timeout"] = t if t > 0 else None
        return cls(**defaults)

    def delay(self, attempt: int) -> float:
        """Backoff before retry number `attempt` (0-based)."""
        base = min(self.max_delay, self.base_delay * (2.0 ** attempt))
        return base * (1.0 + self.jitter * self._rng.random())

    def _run_once(self, fn: Callable, args, kw):
        if self.attempt_timeout is None:
            return fn(*args, **kw)
        import threading
        box: dict = {}

        def runner():
            try:
                box["result"] = fn(*args, **kw)
            except BaseException as e:
                box["error"] = e

        # a daemon thread, NOT an executor: abandoned attempts must neither
        # block the next attempt nor pin interpreter exit (3.9+ executor
        # threads are joined at shutdown)
        t = threading.Thread(target=runner, daemon=True)
        t.start()
        t.join(self.attempt_timeout)
        if t.is_alive():
            raise AttemptTimeout(
                f"attempt exceeded {self.attempt_timeout}s")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def call(self, fn: Callable, *args, op: Optional[str] = None, **kw):
        """Run `fn(*args, **kw)` under this policy; raises
        RetryExhaustedError after the last attempt fails."""
        name = op or getattr(fn, "__name__", "call")
        last: Optional[BaseException] = None
        record = _metrics_mod.enabled()
        for attempt in range(self.max_attempts):
            try:
                result = self._run_once(fn, args, kw)
                if attempt > 0:
                    if record:
                        _M_RECOVERED.inc(op=name)
                    _events_mod.emit("retry_recovered", op=name,
                                     attempts=attempt + 1)
                return result
            except self.retry_on as e:
                last = e
                if attempt + 1 >= self.max_attempts:
                    break
                if record:
                    _M_RETRIES.inc(op=name)
                time.sleep(self.delay(attempt))
        if record:
            _M_EXHAUSTED.inc(op=name)
        _events_mod.emit("retry_exhausted", severity="error", op=name,
                         attempts=self.max_attempts,
                         error=f"{type(last).__name__}: {last}")
        raise RetryExhaustedError(name, self.max_attempts, last)

    def wrap(self, op: Optional[str] = None):
        """Decorator form: @policy.wrap("store.get")."""
        def deco(fn):
            @functools.wraps(fn)
            def inner(*args, **kw):
                return self.call(fn, *args, op=op or fn.__name__, **kw)
            return inner
        return deco


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               op: Optional[str] = None, **kw):
    """One-shot helper: retry `fn` under `policy` (default RetryPolicy())."""
    return (policy or RetryPolicy()).call(fn, *args, op=op, **kw)


def retryable(op: Optional[str] = None,
              policy: Optional[RetryPolicy] = None):
    """Decorator: @retryable("ps.pull_dense", policy=...)."""
    return (policy or RetryPolicy()).wrap(op)
