"""dy2static — AST conversion of tensor-dependent Python control flow.

Reference: the dygraph_to_static transpiler
(`/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:775` + per-construct transformers): Paddle rewrites
`if`/`while`/`for`/bool-ops over tensors into graph ops
(`convert_ifelse`, `convert_while_loop` in `convert_operators.py`).

TPU-native equivalent: plain tracing (jax) already handles everything
EXCEPT data-dependent Python control flow — a traced `if tensor:` either
raises (TracerBoolConversionError) or, worse, a concrete-but-traced branch
is silently baked in. This module closes that gap:

* `ast_transform(fn)` rewrites the function's AST so every `if` / `while` /
  `and` / `or` / `not` goes through a RUNTIME dispatcher;
* the dispatchers (`convert_ifelse`, `convert_while`, `convert_logical_*`)
  keep exact Python semantics when the condition is a concrete value and
  switch to `lax.cond` / `lax.while_loop` / `jnp.logical_*` when it is a
  tracer — so one source supports both eager and `to_static` execution;
* constructs that cannot be converted (a `return`/`break`/`continue` that
  escapes a tensor-dependent branch) raise a PRECISE error at trace time
  instead of jax's generic tracer error.

Scope (documented): branch/loop bodies may contain assignments, nested
control flow and calls. Variables mutated in a converted region become the
`lax.cond` operands / `while_loop` carry, so both branches must leave them
with matching structure (jax enforces; we re-raise with the variable
names). `for` loops keep Python semantics (unrolled under trace — the
jax-idiomatic treatment; use `paddle.jit.not_to_static` or lax.scan for
long dynamic loops).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["ast_transform", "needs_transform", "convert_ifelse",
           "convert_while", "convert_logical_and", "convert_logical_or",
           "convert_logical_not", "Undefined"]


class Undefined:
    """Sentinel for names not yet bound when a converted region starts."""
    _inst: "Optional[Undefined]" = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"

    @staticmethod
    def _err():
        raise NameError(
            "variable used before assignment inside a to_static-converted "
            "branch (it was undefined before the branch and only assigned "
            "in one side)")

    # every plausible use of a poisoned branch-local raises the SAME named
    # diagnostic (ADVICE r2: attribute access / indexing / arithmetic /
    # jnp conversion previously surfaced as confusing AttributeError or
    # TypeError mentioning Undefined internals)
    def __bool__(self):
        self._err()

    def __getattr__(self, name):
        # AttributeError (not NameError) keeps the hasattr / three-arg
        # getattr probing protocols working; the message still names the
        # real cause
        raise AttributeError(
            "variable used before assignment inside a to_static-converted "
            "branch (it was undefined before the branch and only assigned "
            f"in one side); attribute access: .{name}")

    def __call__(self, *a, **k):
        self._err()

    def __iter__(self):
        self._err()

    def __len__(self):
        self._err()

    def __getitem__(self, i):
        self._err()

    def __array__(self, *a, **k):
        self._err()

    def _binop(self, other):
        self._err()

    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _binop
    __truediv__ = __rtruediv__ = __matmul__ = __rmatmul__ = _binop
    __lt__ = __le__ = __gt__ = __ge__ = __mod__ = __pow__ = _binop
    __and__ = __or__ = __xor__ = _binop

    def __neg__(self):
        self._err()


_UNDEF = Undefined()


def _raw(x):
    return x.data if isinstance(x, Tensor) else x


def _is_traced(x) -> bool:
    return isinstance(_raw(x), jax.core.Tracer)


# --------------------------- runtime dispatchers -----------------------------

def _jaxable(v) -> bool:
    import numpy as np
    v = _raw(v)
    return isinstance(v, (jax.Array, jax.core.Tracer, np.ndarray, np.number,
                          np.bool_, int, float, bool, complex))


def convert_ifelse(cond, true_fn: Callable, false_fn: Callable,
                   names: Sequence[str], operands: Tuple):
    """`if cond: ... else: ...` with `names` = variables either side may
    assign; returns their final values.

    Traced path: both branches are probed inline (XLA DCEs the dead probe
    ops) to discover which outputs are tensor-like on BOTH sides — those
    ride `lax.cond`; branch-local temporaries (tensor on one side only, or
    non-tensor) come back as the `Undefined` sentinel, which raises a named
    error if actually used later.
    """
    c = _raw(cond)
    if not _is_traced(c):
        return true_fn(*operands) if c else false_fn(*operands)
    n = len(names)
    defined_idx = [i for i, v in enumerate(operands)
                   if not isinstance(v, Undefined)]

    def call_with(branch, ops_def):
        full = list(operands)
        for j, i in enumerate(defined_idx):
            full[i] = ops_def[j]
        out = branch(*full)
        return tuple(out)

    probe_t = tuple(true_fn(*operands))
    probe_f = tuple(false_fn(*operands))
    carried = [i for i in range(n)
               if _jaxable(probe_t[i]) and _jaxable(probe_f[i])]
    fixed = {}
    for i in range(n):
        if i in carried:
            continue
        if probe_t[i] is probe_f[i]:
            fixed[i] = probe_t[i]  # same object on both sides: bind it
        else:
            fixed[i] = _UNDEF  # branch-local temp; poisoned if used later

    def tf(ops_def):
        out = call_with(true_fn, ops_def)
        return tuple(_raw(out[i]) for i in carried)

    def ff(ops_def):
        out = call_with(false_fn, ops_def)
        return tuple(_raw(out[i]) for i in carried)

    ops = tuple(_raw(operands[i]) for i in defined_idx)
    try:
        res = jax.lax.cond(jnp.asarray(c, bool).reshape(()), tf, ff, ops)
    except TypeError as e:
        raise TypeError(
            f"to_static: the two sides of a tensor-dependent `if` must "
            f"assign matching shapes/dtypes to {list(names)} "
            f"(lax.cond branches differ): {e}") from None
    final = []
    pos = {i: j for j, i in enumerate(carried)}
    for i in range(n):
        if i in pos:
            v = res[pos[i]]
            final.append(Tensor(v) if isinstance(v, jax.Array) else v)
        else:
            final.append(fixed[i])
    return tuple(final)


def convert_while(cond_fn: Callable, body_fn: Callable,
                  names: Sequence[str], operands: Tuple):
    """`while cond: body` with `names` = variables the body assigns.

    Traced path: variables both bound-before-the-loop and tensor-like ride
    the `lax.while_loop` carry; loop-local temporaries (unbound before the
    loop) are recomputed inside each body call and come back `Undefined`
    after the loop — Python leaves them at their last value, so reading
    them afterwards is the (documented) semantic difference.
    """
    c0 = _raw(cond_fn(*operands))
    if not _is_traced(c0):
        vals = tuple(operands)
        while cond_fn(*vals):
            vals = tuple(body_fn(*vals))
        return vals
    n = len(names)
    probe = tuple(body_fn(*operands))
    carried = [i for i in range(n)
               if not isinstance(operands[i], Undefined)
               and _jaxable(operands[i]) and _jaxable(probe[i])]
    fixed = {}
    for i in range(n):
        if i in carried:
            continue
        if probe[i] is operands[i]:
            fixed[i] = operands[i]  # body does not actually change it
        elif isinstance(operands[i], Undefined):
            fixed[i] = _UNDEF  # loop-local temp
        else:
            raise NotImplementedError(
                f"to_static: `while` loop variable '{names[i]}' is not a "
                f"tensor/scalar (got {type(operands[i]).__name__}) and "
                f"changes across iterations — it cannot ride the "
                f"lax.while_loop carry. Hoist it out of the loop or use "
                f"paddle.jit.not_to_static.")

    def call_with(ops_def):
        full = list(operands)
        for j, i in enumerate(carried):
            full[i] = ops_def[j]
        return full

    def cf(ops):
        return jnp.asarray(_raw(cond_fn(*call_with(ops))), bool).reshape(())

    def bf(ops):
        out = tuple(body_fn(*call_with(ops)))
        return tuple(_raw(out[i]) for i in carried)

    ops0 = tuple(_raw(operands[i]) for i in carried)
    # dtypes must be loop-invariant: weak python scalars entering the carry
    # are promoted to their probe dtype up front
    ops0 = tuple(jnp.asarray(o, _raw(probe[i]).dtype
                             if _is_traced(probe[i]) else None)
                 if not isinstance(o, (jax.Array, jax.core.Tracer))
                 else o
                 for o, i in zip(ops0, carried))
    try:
        res = jax.lax.while_loop(cf, bf, ops0)
    except TypeError as e:
        raise TypeError(
            f"to_static: a tensor-dependent `while` must keep the shape/"
            f"dtype of its loop variables {list(names)} fixed across "
            f"iterations (lax.while_loop carry mismatch): {e}") from None
    final = []
    pos = {i: j for j, i in enumerate(carried)}
    for i in range(n):
        if i in pos:
            v = res[pos[i]]
            final.append(Tensor(v) if isinstance(v, jax.Array) else v)
        else:
            final.append(fixed[i])
    return tuple(final)


def convert_logical_and(lhs, rhs_thunk: Callable):
    l = _raw(lhs)
    if _is_traced(l):
        r = _raw(rhs_thunk())
        return Tensor(jnp.logical_and(jnp.asarray(l, bool),
                                      jnp.asarray(r, bool)))
    return rhs_thunk() if l else lhs


def convert_logical_or(lhs, rhs_thunk: Callable):
    l = _raw(lhs)
    if _is_traced(l):
        r = _raw(rhs_thunk())
        return Tensor(jnp.logical_or(jnp.asarray(l, bool),
                                     jnp.asarray(r, bool)))
    return lhs if l else rhs_thunk()


def convert_logical_not(x):
    v = _raw(x)
    if _is_traced(v):
        return Tensor(jnp.logical_not(jnp.asarray(v, bool)))
    return not v


def assert_not_traced(cond, construct: str, detail: str):
    """Loud diagnostic for control flow we cannot convert."""
    if _is_traced(cond):
        raise NotImplementedError(
            f"to_static: {construct} depends on a traced tensor but cannot "
            f"be converted to lax control flow because {detail}. "
            f"Restructure the code (e.g. hoist the `return` out of the "
            f"branch, or compute both results and select with "
            f"paddle.where), or exempt the function with "
            f"paddle.jit.not_to_static.")
    return cond


# ----------------------------- AST analysis ---------------------------------

class _ScopedStoreCollector(ast.NodeVisitor):
    """Names assigned at the scope of the visited statements — does NOT
    descend into nested function/class/lambda/comprehension scopes."""

    def __init__(self):
        self.names: List[str] = []

    def _add(self, name):
        if name not in self.names:
            self.names.append(name)

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self._add(node.id)

    def visit_FunctionDef(self, node):
        self._add(node.name)  # the def itself binds a name

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._add(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_ListComp(self, node):
        for gen in node.generators:
            self.visit(gen.iter)

    visit_SetComp = visit_DictComp = visit_GeneratorExp = visit_ListComp


def _stored_names(stmts: Sequence[ast.stmt]) -> List[str]:
    c = _ScopedStoreCollector()
    for s in stmts:
        c.visit(s)
    return c.names


class _EscapeFinder(ast.NodeVisitor):
    """Finds return/break/continue that would escape the given body."""

    def __init__(self):
        self.has_return = False
        self.has_break = False
        self._loop_depth = 0

    def visit_Return(self, node):
        self.has_return = True

    def visit_Break(self, node):
        if self._loop_depth == 0:
            self.has_break = True

    def visit_Continue(self, node):
        if self._loop_depth == 0:
            self.has_break = True

    def visit_For(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = visit_AsyncFor = visit_For

    def visit_FunctionDef(self, node):
        pass  # nested scope

    visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef


def _escapes(stmts: Sequence[ast.stmt]) -> bool:
    f = _EscapeFinder()
    for s in stmts:
        f.visit(s)
    return f.has_return or f.has_break


def needs_transform(fn: Callable) -> bool:
    """True if fn's source contains constructs worth rewriting (if / while /
    bool ops) — the trace-only fast path is kept otherwise."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return False
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While, ast.BoolOp, ast.Not)):
            return True
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return True
    return False


# ----------------------------- AST transform --------------------------------

_HELPERS = {
    "__dy2s_ifelse": convert_ifelse,
    "__dy2s_while": convert_while,
    "__dy2s_and": convert_logical_and,
    "__dy2s_or": convert_logical_or,
    "__dy2s_not": convert_logical_not,
    "__dy2s_assert_plain": assert_not_traced,
    "__dy2s_undef": _UNDEF,
}


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _try_fetch(tmp: str, name: str) -> List[ast.stmt]:
    """tmp = name if bound else __dy2s_undef (as a try/except statement)."""
    return [ast.Try(
        body=[ast.Assign(targets=[_store(tmp)], value=_load(name))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(elts=[_load("NameError"),
                                 _load("UnboundLocalError")], ctx=ast.Load()),
            name=None,
            body=[ast.Assign(targets=[_store(tmp)],
                             value=_load("__dy2s_undef"))])],
        orelse=[], finalbody=[])]


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0

    def _uid(self):
        self.counter += 1
        return self.counter

    # ---- boolean operators --------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = "__dy2s_and" if isinstance(node.op, ast.And) else "__dy2s_or"
        expr = node.values[0]
        for rhs in node.values[1:]:
            expr = ast.Call(
                func=_load(op),
                args=[expr, ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                       kw_defaults=[], defaults=[]),
                    body=rhs)],
                keywords=[])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                ast.Call(func=_load("__dy2s_not"), args=[node.operand],
                         keywords=[]), node)
        return node

    # ---- if -----------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        uid = self._uid()
        cond_name = f"__dy2s_c{uid}"
        names = _stored_names(node.body + node.orelse)
        out: List[ast.stmt] = [
            ast.Assign(targets=[_store(cond_name)], value=node.test)]
        if _escapes(node.body) or _escapes(node.orelse) or not names:
            # cannot build branch functions: keep the Python `if`, but make
            # a tensor condition fail with a precise diagnostic
            reason = ("a branch contains return/break/continue that leaves "
                      "the branch" if (_escapes(node.body)
                                       or _escapes(node.orelse))
                      else "its branches assign no variables to carry")
            guard = ast.Expr(value=ast.Call(
                func=_load("__dy2s_assert_plain"),
                args=[_load(cond_name),
                      ast.Constant(value="an `if` statement"),
                      ast.Constant(value=reason)], keywords=[]))
            new_if = ast.If(test=_load(cond_name), body=node.body,
                            orelse=node.orelse)
            return [ast.copy_location(s, node)
                    for s in out + [guard, new_if]]

        tname, fname = f"__dy2s_t{uid}", f"__dy2s_f{uid}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(elts=[_load(n) for n in names],
                                         ctx=ast.Load()))
        tdef = ast.FunctionDef(name=tname, args=args,
                               body=node.body + [ret], decorator_list=[])
        fdef = ast.FunctionDef(name=fname, args=args,
                               body=(node.orelse or [ast.Pass()]) + [ret],
                               decorator_list=[])
        out += [tdef, fdef]
        opnames = []
        for n in names:
            tmp = f"__dy2s_v{uid}_{len(opnames)}"
            out += _try_fetch(tmp, n)
            opnames.append(tmp)
        call = ast.Call(
            func=_load("__dy2s_ifelse"),
            args=[_load(cond_name), _load(tname), _load(fname),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[_load(t) for t in opnames],
                            ctx=ast.Load())],
            keywords=[])
        out.append(ast.Assign(
            targets=[ast.Tuple(elts=[_store(n) for n in names],
                               ctx=ast.Store())],
            value=call))
        return [ast.copy_location(s, node) for s in out]

    # ---- while --------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        uid = self._uid()
        names = _stored_names(node.body)
        if _escapes(node.body) or node.orelse or not names:
            cond_name = f"__dy2s_c{uid}"
            reason = ("the loop body contains return/break/continue"
                      if _escapes(node.body) else
                      ("`while ... else` is not convertible" if node.orelse
                       else "the loop body assigns no variables to carry"))
            pre = ast.Assign(targets=[_store(cond_name)], value=node.test)
            guard = ast.Expr(value=ast.Call(
                func=_load("__dy2s_assert_plain"),
                args=[_load(cond_name),
                      ast.Constant(value="a `while` loop"),
                      ast.Constant(value=reason)], keywords=[]))
            new_while = ast.While(test=node.test, body=node.body,
                                  orelse=node.orelse)
            return [ast.copy_location(s, node)
                    for s in [pre, guard, new_while]]

        cname, bname = f"__dy2s_wc{uid}", f"__dy2s_wb{uid}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cdef = ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[])
        ret = ast.Return(value=ast.Tuple(elts=[_load(n) for n in names],
                                         ctx=ast.Load()))
        bdef = ast.FunctionDef(name=bname, args=args,
                               body=node.body + [ret], decorator_list=[])
        out: List[ast.stmt] = [cdef, bdef]
        opnames = []
        for n in names:
            tmp = f"__dy2s_v{uid}_{len(opnames)}"
            out += _try_fetch(tmp, n)
            opnames.append(tmp)
        call = ast.Call(
            func=_load("__dy2s_while"),
            args=[_load(cname), _load(bname),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[_load(t) for t in opnames],
                            ctx=ast.Load())],
            keywords=[])
        out.append(ast.Assign(
            targets=[ast.Tuple(elts=[_store(n) for n in names],
                               ctx=ast.Store())],
            value=call))
        return [ast.copy_location(s, node) for s in out]


_transform_cache: Dict[Any, Callable] = {}


def ast_transform(fn: Callable) -> Callable:
    """Return fn with tensor-convertible control flow, or fn itself when the
    source is unavailable / contains nothing to rewrite."""
    key = getattr(fn, "__wrapped__", fn)
    if key in _transform_cache:
        return _transform_cache[key]
    if not needs_transform(fn):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # avoid re-running to_static et al on exec
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    glb = dict(fn.__globals__)
    glb.update(_HELPERS)
    # closures: snapshot free-variable cells into the namespace (late
    # rebinding of closed-over names is not tracked — document & accept)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass
    code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    ns: Dict[str, Any] = {}
    exec(code, glb, ns)
    new_fn = ns[fdef.name]
    new_fn = functools.wraps(fn)(new_fn)
    _transform_cache[key] = new_fn
    return new_fn
