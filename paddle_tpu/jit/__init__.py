"""paddle_tpu.jit — eager->compiled capture (dygraph->static equivalent).

Reference: `paddle.jit.to_static` (the dy2static AST transpiler,
`/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/`) and
`paddle.jit.save/load` (`fluid/dygraph/jit.py`). On TPU there is no AST
rewriting: JAX tracing captures the Python forward directly. The captured
artifact (`Program`) is an XLA executable keyed by input shapes — the
StandaloneExecutor equivalent is XLA's own scheduler.

`functionalize(layer)` is the core bridge: it swaps every Parameter/buffer's
array for traced values, runs the eager forward, and returns a pure function
`(params, buffers, rng, *inputs) -> (out, new_buffers)` usable under
jax.jit/grad/shard_map.
"""
from __future__ import annotations

import contextlib
import functools
import os
import pickle
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as random_mod
from ..framework import tape as tape_mod
from ..framework.tensor import Tensor
from ..nn.layer import Layer
from ..profiler import compile_watch as _compile_watch
from ..profiler.watchdog import get_watchdog as _get_watchdog


def _tree_to_arrays(x):
    return jax.tree_util.tree_map(
        lambda t: t.data if isinstance(t, Tensor) else t, x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _analysis_enabled(entry: str) -> bool:
    """Fast gate for the PADDLE_TPU_AUDIT trace-time hook: the common
    (disarmed) case is one env read, no analysis import."""
    raw = os.environ.get("PADDLE_TPU_AUDIT", "").strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return False
    from .. import analysis
    return analysis.enabled(entry)


@contextlib.contextmanager
def _swapped_state(layer: Layer, params: Dict[str, Any], buffers: Dict[str, Any]):
    """Temporarily rebind parameter/buffer arrays (possibly tracers)."""
    named_p = dict(layer.named_parameters())
    named_b = dict(layer.named_buffers())
    saved_p = {k: p.data for k, p in named_p.items()}
    saved_b = {k: b.data for k, b in named_b.items()}
    try:
        for k, v in params.items():
            if k in named_p:
                named_p[k].data = v
        for k, v in buffers.items():
            if k in named_b:
                named_b[k].data = v
        yield named_b
    finally:
        for k, p in named_p.items():
            p.data = saved_p[k]
        for k, b in named_b.items():
            b.data = saved_b[k]


def functionalize(layer: Layer):
    """Return (apply_fn, params, buffers).

    apply_fn(params, buffers, rng_key, *inputs, **kw) -> (outputs, new_buffers)
    where params/buffers are dicts name->jax.Array and outputs are raw arrays.
    """
    params0 = {k: p.data for k, p in layer.named_parameters()}
    buffers0 = {k: b.data for k, b in layer.named_buffers()}

    def apply_fn(params, buffers, rng_key, *inputs, **kw):
        tensor_inputs = jax.tree_util.tree_map(
            lambda a: Tensor(a) if isinstance(a, jax.Array) else a, inputs)
        with tape_mod.no_grad(), _swapped_state(layer, params, buffers) as named_b:
            ctx = random_mod.rng_scope(rng_key) if rng_key is not None \
                else contextlib.nullcontext()
            with ctx:
                out = layer(*tensor_inputs, **kw)
            new_buffers = {k: b.data for k, b in named_b.items()}
        return _tree_to_arrays(out), new_buffers

    return apply_fn, params0, buffers0


class Program:
    """Captured compiled program keyed by input signature.

    The serializable static-graph artifact (ProgramDesc equivalent,
    reference `framework/framework.proto:236`): jaxpr + in/out tree specs.
    """

    def __init__(self, fn: Callable, jit_kwargs: Optional[dict] = None):
        self.fn = fn
        self._jitted = jax.jit(fn, **(jit_kwargs or {}))

    def __call__(self, *args, **kw):
        return self._jitted(*args, **kw)

    @property
    def jaxpr(self):
        return None  # filled per-signature via jax.make_jaxpr on demand

    def lower(self, *args, **kw):
        return self._jitted.lower(*args, **kw)


class StaticLayer:
    """`to_static(layer)` result: eager-looking API, compiled execution."""

    _seq = 0

    def __init__(self, layer: Layer, jit_kwargs: Optional[dict] = None):
        self.layer = layer
        self._maybe_convert_forward(layer)
        self.apply_fn, _, _ = functionalize(layer)
        self._jitted = jax.jit(self.apply_fn, static_argnames=())
        # watchdog key is PER INSTANCE (the jit cache is too): keying by
        # class name made a second instance's first compile look like a
        # retrace, and per-instance recompiles look like hits
        StaticLayer._seq += 1
        self._wd_name = f"{type(layer).__name__}#{StaticLayer._seq}"

    @staticmethod
    def _maybe_convert_forward(layer: Layer):
        """dy2static: rewrite tensor-dependent `if`/`while` in forward() into
        lax control flow (reference ProgramTranslator AST transpile,
        `dygraph_to_static/program_translator.py:775`). Trace-only remains
        the fast path for control-flow-free forwards."""
        import types
        from . import dy2static
        fwd = type(layer).forward
        if getattr(fwd, "_dy2s_converted", False) or \
                getattr(layer.forward, "__func__", None) is not fwd:
            return
        if dy2static.needs_transform(fwd):
            new_fwd = dy2static.ast_transform(fwd)
            if new_fwd is not fwd:
                new_fwd._dy2s_converted = True
                object.__setattr__(layer, "forward",
                                   types.MethodType(new_fwd, layer))

    def audit(self, *inputs, emit: bool = True):
        """Statically audit the compiled forward on this input signature
        (trace + lower only). Returns an analysis.AuditReport."""
        from .. import analysis
        params = {k: p.data for k, p in self.layer.named_parameters()}
        buffers = {k: b.data for k, b in self.layer.named_buffers()}
        arr_inputs = tuple(_tree_to_arrays(inputs))
        return analysis.audit_program(
            self.apply_fn,
            (params, buffers, jax.random.PRNGKey(0)) + arr_inputs,
            name=self._wd_name, entry="to_static", emit=emit)

    def __call__(self, *inputs, **kw):
        params = {k: p.data for k, p in self.layer.named_parameters()}
        buffers = {k: b.data for k, b in self.layer.named_buffers()}
        arr_inputs = _tree_to_arrays(inputs)
        if _analysis_enabled("to_static") and not kw:
            from .. import analysis
            analysis.maybe_audit(
                "to_static", self._wd_name, self.apply_fn,
                (params, buffers, jax.random.PRNGKey(0))
                + tuple(arr_inputs))
        # retrace watchdog: a new input signature means jax.jit re-traces
        # the whole forward — surface WHAT changed (params/buffers keep
        # their shapes, so the data inputs AND kw leaves key the signature)
        _get_watchdog().observe(
            "to_static", self._wd_name,
            jax.tree_util.tree_leaves(arr_inputs)
            + jax.tree_util.tree_leaves(kw))
        rng = random_mod.default_generator().split() if self.layer.training else \
            jax.random.PRNGKey(0)
        _cw_prev = _compile_watch.push_entry("to_static", self._wd_name)
        try:
            out, new_buffers = self._jitted(params, buffers, rng,
                                            *arr_inputs, **kw)
        finally:
            _compile_watch.pop_entry(_cw_prev)
        named_b = dict(self.layer.named_buffers())
        for k, v in new_buffers.items():
            if k in named_b:
                named_b[k].data = v
        return jax.tree_util.tree_map(Tensor, out)

    # passthroughs
    def __getattr__(self, name):
        return getattr(self.layer, name)


def _collect_captured_tensors(fn) -> list:
    """Tensors a function captures — through closure cells OR module globals
    its code actually references (directly, through a Layer, or a few
    container levels deep). This is the state that must stay LIVE when the
    function is compiled once and reused (reference: captured Parameters
    become graph Variables whose values track updates); anything reachable
    only through deeper indirection is frozen at trace time."""
    out, seen = [], set()

    def collect(v, depth=0):
        if id(v) in seen or depth > 3:
            return
        seen.add(id(v))
        if isinstance(v, Tensor):
            out.append(v)
        elif isinstance(v, Layer):
            for _, p in v.named_parameters():
                collect(p, depth + 1)
            for _, b in v.named_buffers():
                collect(b, depth + 1)
        elif isinstance(v, (list, tuple)):
            for x in v:
                collect(x, depth + 1)
        elif isinstance(v, dict):
            for x in v.values():
                collect(x, depth + 1)

    for cell in (getattr(fn, "__closure__", None) or ()):
        try:
            collect(cell.cell_contents)
        except ValueError:
            pass
    code = getattr(fn, "__code__", None)
    glb = getattr(fn, "__globals__", None)
    if code is not None and glb is not None:
        for name in code.co_names:  # only names the code references
            if name in glb:
                collect(glb[name])
    return out


def to_static(layer_or_fn=None, input_spec=None, build_strategy=None, **kw):
    """Decorator/wrapper: Layer -> StaticLayer, function -> jitted function.
    Honors `paddle.jit.enable_to_static(False)` (ProgramTranslator gate):
    when disabled, conversion is a no-op and the eager object runs as-is."""
    def convert(obj):
        if not ProgramTranslator.enabled:
            return obj
        if isinstance(obj, Layer):
            return StaticLayer(obj)
        from . import dy2static
        raw = obj
        if dy2static.needs_transform(obj):
            obj = dy2static.ast_transform(obj)
        if obj is not raw:
            # ast_transform snapshots closure cells into globals, so cell
            # REBINDING can't reach the transformed body anyway (documented
            # in dy2static) — a convert-time snapshot of the same objects is
            # exactly what the transformed code uses
            snapshot = _collect_captured_tensors(raw)
            collect = lambda: snapshot
        else:
            # re-read cells/globals per call: `nonlocal w; w = new_tensor`
            # (or a module-global rebind) must swap the NEW object's data
            # in, not keep threading the old one
            collect = lambda: _collect_captured_tensors(raw)
        # shared per-call state: the wrapper refreshes the tensor list, the
        # traced body swaps those exact objects — one source of truth
        state = {"tensors": collect()}

        _to_static_seq[0] += 1
        fn_name = (getattr(obj, "__qualname__",
                           getattr(obj, "__name__", "fn"))
                   + f"#{_to_static_seq[0]}")  # per-conversion watchdog key:
        # each convert() owns a fresh jit cache, so two conversions of the
        # same function must not share retrace bookkeeping

        # ONE jitted callable per conversion: defining it inside the wrapper
        # rebuilt the jit object per call, so jax's cache never hit and every
        # invocation re-traced+recompiled (and the watchdog, which dedups by
        # signature, reported the site as retrace-free — a false all-clear).
        # Captured Tensors (closure cells + referenced module globals) are
        # threaded as ARGUMENTS (not baked in as trace constants) so
        # optimizer updates stay visible, and a fresh rng key per call keeps
        # stochastic ops stochastic; state behind deeper indirection than
        # _collect_captured_tensors walks is frozen — thread it explicitly.
        @jax.jit
        def pure(aux, key, *a):
            tensors = state["tensors"]
            saved = [t.data for t in tensors]
            try:
                for t, v in zip(tensors, aux):
                    t.data = v
                with random_mod.rng_scope(key):
                    out = obj(*jax.tree_util.tree_map(
                        lambda x: Tensor(x) if isinstance(x, jax.Array)
                        else x, a))
                return _tree_to_arrays(out)
            finally:
                for t, v in zip(tensors, saved):
                    t.data = v

        @functools.wraps(obj)
        def wrapper(*args, **kwargs):
            if kwargs:
                # silently tracing with defaults would return WRONG results;
                # fail loudly until kwargs are threaded through the jit
                raise TypeError(
                    f"to_static function {fn_name!r} was called with keyword "
                    f"arguments {sorted(kwargs)} — the compiled path passes "
                    f"positional arguments only; pass them positionally or "
                    f"exempt the function with paddle.jit.not_to_static")
            arrs = _tree_to_arrays(args)
            state["tensors"] = collect()
            aux = tuple(t.data for t in state["tensors"])
            # aux is part of the jit signature too: a closure tensor whose
            # shape/dtype/count changes re-traces just like an input change
            _get_watchdog().observe(
                "to_static", fn_name,
                jax.tree_util.tree_leaves(arrs) + list(aux))
            if _analysis_enabled("to_static"):
                from .. import analysis
                analysis.maybe_audit(
                    "to_static", fn_name, pure.__wrapped__,
                    (aux, jax.random.PRNGKey(0)) + tuple(arrs))
            _cw_prev = _compile_watch.push_entry("to_static", fn_name)
            try:
                out = pure(aux, random_mod.default_generator().split(), *arrs)
            finally:
                _compile_watch.pop_entry(_cw_prev)
            return jax.tree_util.tree_map(Tensor, out)
        return wrapper

    if layer_or_fn is None:
        return convert
    return convert(layer_or_fn)


_to_static_seq = [0]


# ---------------------------------------------------------------------------
# TrainStep: whole-train-step compilation (forward+backward+optimizer in ONE
# XLA executable — the TPU answer to the reference's InterpreterCore hot loop)
# ---------------------------------------------------------------------------
class TrainStep:
    _seq = 0

    def __init__(self, layer: Layer, loss_fn: Callable, optimizer,
                 donate: bool = True, amp_dtype=None, health=None,
                 fused_opt=None):
        """amp_dtype: e.g. jnp.bfloat16 enables O2 mixed precision — fp32
        master weights and optimizer slots, parameters cast to amp_dtype for
        the forward/backward compute (reference AMP level O2, master-weight
        pattern in imperative/amp_auto_cast.h + GradScaler; bf16 on TPU
        needs no loss scaling).

        health: fold the in-graph numerics sentinel (profiler/health.py
        HealthProbe) into the compiled step — loss, any-nonfinite flag,
        global + per-layer-group grad norms and update/param ratio are
        computed on-device in the SAME XLA program and fetched as one
        tiny vector every PADDLE_TPU_HEALTH_INTERVAL steps. None (the
        default) follows PADDLE_TPU_HEALTH=1 / FLAGS_check_nan_inf; a
        sentinel trip triggers a one-shot eager replay of the last batch
        with the per-op NaN checks armed (first-NaN attribution).

        fused_opt: run the optimizer update as ONE grouped multi-tensor
        apply over the flattened parameter leaves (bit-identical to the
        sequential per-parameter loop — Optimizer.apply_fn(fused=True))
        instead of ~n_params small fused loops. None (the default)
        follows PADDLE_TPU_FUSED_OPT (on unless set to 0); either way it
        only engages when the optimizer's update is elementwise
        (optimizer.fused_update_supported).

        NOTE on recompute: a whole-forward jax.checkpoint here is a
        measured no-op for peak memory (XLA already frees residuals as the
        fused backward consumes them: ResNet-50 4.67->4.68GB temp, GPT-2
        4.21->4.39GB) while costing ~25% step time, so TrainStep does not
        offer it. Remat pays off where it bounds SCAN residuals — the
        micro-batch loop in meta_parallel/engine.py (strategy.recompute)
        and the per-tick stage apply in pipeline_parallel.py."""
        self.layer = layer
        self.optimizer = optimizer
        self.apply_fn, params, buffers = functionalize(layer)
        # private copies: donate_argnums consumes these buffers each step and
        # must not invalidate the eager Layer's arrays
        self.params = jax.tree_util.tree_map(jnp.copy, params)
        self.buffers = jax.tree_util.tree_map(jnp.copy, buffers)
        self.opt_state = optimizer.init_state_tree(params)
        self._t = 0
        loss_fn_ = loss_fn
        self._loss_fn = loss_fn
        from ..profiler import health as _health_mod
        if health is None:
            health = _health_mod.enabled()
        self._health_probe = _health_mod.HealthProbe(params) if health \
            else None
        self._health_interval = _health_mod.interval()
        self._last_batch = None   # raw arrays, kept only while health is on
        self._nan_replayed = False
        self.last_health = None   # newest decoded sentinel stats
        self.last_attribution = None
        health_probe = self._health_probe

        def maybe_cast(p):
            if amp_dtype is None:
                return p
            return jax.tree_util.tree_map(
                lambda a: a.astype(amp_dtype)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, p)

        def cast_inputs(batch):
            # O2 "pure" mode also feeds the network amp-dtype ACTIVATIONS
            # (reference amp O2): without this, fp32 inputs (images) drag
            # every conv back to fp32 because kernels follow the activation
            # dtype. Labels/ids are integral and pass through.
            if amp_dtype is None:
                return batch
            return tuple(a.astype(amp_dtype)
                         if jnp.issubdtype(a.dtype, jnp.floating) else a
                         for a in batch)

        if fused_opt is None:
            fused_opt = str(os.environ.get(
                "PADDLE_TPU_FUSED_OPT", "1")).strip().lower() \
                not in ("0", "false", "off", "no")
        fused_opt = bool(fused_opt) and getattr(
            optimizer, "fused_update_supported", False)
        self.fused_opt = fused_opt

        def step(params, buffers, opt_state, rng, lr, t, *batch):
            batch = cast_inputs(batch[:-1]) + (batch[-1],)
            def loss_of(p):
                out, new_buffers = self.apply_fn(maybe_cast(p), buffers, rng,
                                                 *batch[:-1])
                # named scope -> XLA op metadata: the loss segment is
                # separable in measured (xplane) per-segment attribution
                with jax.named_scope("loss"):
                    loss = loss_fn_(jax.tree_util.tree_map(Tensor, out),
                                    Tensor(batch[-1]))
                return (loss.data if isinstance(loss, Tensor) else loss), new_buffers
            (loss, new_buffers), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
            with jax.named_scope("optimizer"):
                # pass the kwarg only when fusing: duck-typed optimizers
                # implementing the pre-r06 apply_fn(params, grads, state,
                # lr, t) protocol must keep working unchanged
                if fused_opt:
                    new_params, new_opt = optimizer.apply_fn(
                        params, grads, opt_state, lr=lr, t=t, fused=True)
                else:
                    new_params, new_opt = optimizer.apply_fn(
                        params, grads, opt_state, lr=lr, t=t)
            if health_probe is None:
                return loss, new_params, new_buffers, new_opt
            # in-graph sentinel: a handful of tiny fused reductions, one
            # extra (small) output — never a per-tensor host sync
            hvec = health_probe.stats_vec(loss, grads, params, new_params)
            return loss, new_params, new_buffers, new_opt, hvec

        donate_args = (0, 2) if donate else ()
        self._step = jax.jit(step, static_argnames=(),
                             donate_argnums=donate_args)
        # kept for the static program auditor: audit() re-traces this
        # closure (never the consumed jit object) without executing
        self._step_raw = step
        self._donate_argnums = donate_args
        TrainStep._seq += 1
        self._wd_name = f"{type(layer).__name__}#{TrainStep._seq}"

    def audit(self, *batch, emit: bool = True):
        """Statically audit the compiled step program for perf hazards
        (donation, dtype hygiene, collectives, baked constants) on this
        batch signature — trace + lower only, nothing executes. Returns
        an analysis.AuditReport."""
        from .. import analysis
        arrs = tuple(_tree_to_arrays(batch))
        rng = jax.random.PRNGKey(0)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        return analysis.audit_program(
            self._step_raw,
            (self.params, self.buffers, self.opt_state, rng, lr,
             self._t + 1) + arrs,
            donate_argnums=self._donate_argnums,
            name=self._wd_name, entry="train_step", emit=emit)

    def __call__(self, *batch):
        self._t += 1
        rng = random_mod.default_generator().split()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        arrs = _tree_to_arrays(batch)
        # a new batch signature recompiles the WHOLE fused step — the most
        # expensive retrace in the system; always worth an event
        _get_watchdog().observe("train_step", self._wd_name,
                                jax.tree_util.tree_leaves(arrs))
        if _analysis_enabled("train_step"):
            from .. import analysis
            # batch args stay UNflattened: the audit must trace the same
            # signature the real self._step(..., *arrs) call compiles
            analysis.maybe_audit(
                "train_step", self._wd_name, self._step_raw,
                (self.params, self.buffers, self.opt_state,
                 jax.random.PRNGKey(0), lr, self._t) + tuple(arrs),
                donate_argnums=self._donate_argnums)
        _cw_prev = _compile_watch.push_entry("train_step", self._wd_name)
        try:
            if self._health_probe is None:
                loss, self.params, self.buffers, self.opt_state = self._step(
                    self.params, self.buffers, self.opt_state, rng, lr,
                    self._t, *arrs)
            else:
                (loss, self.params, self.buffers, self.opt_state,
                 hvec) = self._step(
                    self.params, self.buffers, self.opt_state, rng, lr,
                    self._t, *arrs)
        finally:
            _compile_watch.pop_entry(_cw_prev)
        if self._health_probe is not None:
            self._last_batch = arrs
            if self._t % self._health_interval == 0:
                self._note_health(hvec)
        return Tensor(loss)

    def _note_health(self, hvec):
        """Fetch + record one sentinel vector (the tier's single
        device->host transfer); on a fresh trip, run the one-shot eager
        replay for first-NaN attribution. Never raises."""
        from ..profiler import health as _health_mod
        try:
            stats = self._health_probe.decode(hvec)
            self.last_health = _health_mod.record_step_stats(
                stats, step=self._t, source="sentinel")
        except Exception:
            return
        if not stats.get("nonfinite"):
            self._nan_replayed = False
            return
        if self._nan_replayed:
            return
        self._nan_replayed = True  # one replay per trip, not per step
        try:
            self.sync_to_layer()
            self.last_attribution = _health_mod.eager_replay(
                self.layer, self._loss_fn, self._last_batch)
        except Exception:
            pass

    def state_dict(self):
        """Optimizer-slot state of the compiled step (for checkpoint/resume)."""
        flat, _ = jax.tree_util.tree_flatten(self.opt_state)
        return {"t": self._t,
                "opt_flat": [np.asarray(x) if isinstance(x, jax.Array) else x
                             for x in flat]}

    def set_state_dict(self, sd):
        flat, treedef = jax.tree_util.tree_flatten(self.opt_state)
        saved = sd["opt_flat"]
        if len(saved) != len(flat):
            raise ValueError(
                f"opt state mismatch: checkpoint has {len(saved)} leaves, "
                f"model needs {len(flat)}")
        new_flat = [jnp.asarray(v) if isinstance(o, jax.Array) else v
                    for o, v in zip(flat, saved)]
        self.opt_state = jax.tree_util.tree_unflatten(treedef, new_flat)
        self._t = int(sd["t"])

    def sync_to_layer(self):
        """Write compiled-side params back into the eager Layer."""
        named = dict(self.layer.named_parameters())
        for k, v in self.params.items():
            named[k].data = v
        named_b = dict(self.layer.named_buffers())
        for k, v in self.buffers.items():
            if k in named_b:
                named_b[k].data = v


# ---------------------------------------------------------------------------
# save/load (TranslatedLayer equivalent via jax.export StableHLO)
# ---------------------------------------------------------------------------
def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save: params + (optionally) exported StableHLO forward."""
    from ..framework.io import save as fsave
    state = {k: v for k, v in layer.state_dict().items()}
    fsave(state, path + ".pdiparams")
    # a previous export must never outlive the params it was traced with —
    # it is re-created below only when input_spec is given and export works
    if os.path.exists(path + ".pdmodel"):
        os.remove(path + ".pdmodel")
    meta = {"class": type(layer).__name__, "jit_saved": True}
    if input_spec is not None:
        meta["n_inputs"] = len(input_spec)
        apply_fn, params, buffers = functionalize(layer)
        # Predictor/TranslatedLayer must split the flat state_dict back into
        # the (params, buffers) trees of the exported signature
        meta["buffer_keys"] = sorted(buffers.keys())
        arr_spec = [jax.ShapeDtypeStruct(tuple(s.shape), s.dtype)
                    if hasattr(s, "shape") else s for s in input_spec]
        try:
            from jax import export as jexport
            exp = jexport.export(jax.jit(
                lambda p, b, *xs: apply_fn(p, b, None, *xs)[0]))(
                params, buffers, *arr_spec)
            with open(path + ".pdmodel", "wb") as f:
                f.write(exp.serialize())
            meta["exported"] = True
        except Exception as e:
            meta["exported"] = False
            meta["export_error"] = str(e)
            # never leave a stale export behind: a previous .pdmodel would be
            # silently executed against the NEW params by load()/Predictor
            if os.path.exists(path + ".pdmodel"):
                os.remove(path + ".pdmodel")
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


def load(path, **configs):
    from ..framework.io import load as fload
    state = fload(path + ".pdiparams")
    exported = None
    meta = {}
    if os.path.exists(path + ".pdmeta"):
        with open(path + ".pdmeta", "rb") as f:
            meta = pickle.load(f)
    if os.path.exists(path + ".pdmodel"):
        from jax import export as jexport
        with open(path + ".pdmodel", "rb") as f:
            exported = jexport.deserialize(f.read())
    buffer_keys = set(meta.get("buffer_keys", []))

    class TranslatedLayer:
        def __init__(self):
            self.state = state
            self.exported = exported

        def state_dict(self):
            return self.state

        def __call__(self, *inputs):
            if self.exported is None:
                raise RuntimeError("no exported program; only state_dict available")
            arrays = {k: (v.data if isinstance(v, Tensor)
                          else jnp.asarray(np.asarray(v)))
                      for k, v in self.state.items()}
            # exported signature: (params, buffers, *inputs)
            params = {k: v for k, v in arrays.items() if k not in buffer_keys}
            buffers = {k: v for k, v in arrays.items() if k in buffer_keys}
            arrs = _tree_to_arrays(inputs)
            out = self.exported.call(params, buffers, *arrs)
            return jax.tree_util.tree_map(Tensor, out)

    return TranslatedLayer()


not_to_static = lambda fn: fn  # parity no-op


# --------------------- completion: remaining jit exports --------------------

TranslatedLayer = None  # class is created per-load; exposed for isinstance


def _get_translated_layer_class():
    return TranslatedLayer


class TracedLayer:
    """reference jit TracedLayer (dygraph trace -> static program)."""

    def __init__(self, program, parameters):
        self._program = program
        self._params = parameters

    @staticmethod
    def trace(layer, inputs):
        st = to_static(layer)
        out = st(*inputs)
        return out, TracedLayer(st, layer.parameters())

    def __call__(self, *inputs):
        return self._program(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        target = self._program.layer if isinstance(self._program, StaticLayer) \
            else self._program
        save(target, path)


class ProgramTranslator:
    """reference dy2static ProgramTranslator singleton: toggles to_static
    globally (tracing-based here, so 'enable' simply gates conversion)."""

    _instance = None
    enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool):
        type(self).enabled = bool(enable_to_static)


def enable_to_static(flag: bool = True):
    ProgramTranslator.get_instance().enable(flag)


_verbosity = 0


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    global _verbosity
    _verbosity = int(level)


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    set_verbosity(level, also_to_stdout)
