"""Version compatibility shims for the small set of jax APIs whose import
path moved between the jax releases this repo runs against.

Everything here is a re-export: callers use identical semantics on either
side. Keep this module dependency-free (imported very early).
"""
from __future__ import annotations

# shard_map: `jax.shard_map` (new) vs `jax.experimental.shard_map` (old).
# The old entry point also predates two keyword renames the callers use:
# `axis_names={...}` (old spelling: `auto=` holds the COMPLEMENT set) and
# `check_vma=` (old spelling: `check_rep=`), so the fallback is a thin
# translating wrapper, not a bare re-export.
try:
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, **kw):
        if axis_names is not None:
            # old shard_map's `auto=` (the complement set) raises
            # NotImplementedError when executed eagerly, so go FULL manual:
            # axes absent from the specs are replicated per device, which is
            # numerically identical for bodies that only use collectives
            # over `axis_names`. check_rep must be off — the replication
            # checker predates several collectives these kernels use.
            kw.setdefault("check_rep", False)
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)

# Pallas TPU compiler params: `CompilerParams` (new) vs `TPUCompilerParams`
# (old). Both accept dimension_semantics as strings, which is what the
# PARALLEL/ARBITRARY constants below are for — the GridDimensionSemantics
# enum only exists on the new side.
try:
    from jax.experimental.pallas.tpu import CompilerParams as TPUCompilerParams  # type: ignore
except ImportError:  # pragma: no cover
    from jax.experimental.pallas.tpu import TPUCompilerParams  # noqa: F401

DIM_PARALLEL = "parallel"
DIM_ARBITRARY = "arbitrary"


# jax.lax.axis_size arrived after 0.4.x; psum(1, axis) is the portable form
def axis_size(axis_name):
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
