"""Legacy reader decorators (reference `python/paddle/reader/decorator.py`):
composable generator transforms predating DataLoader — kept because PS/CTR
scripts and `train_from_dataset` flows still build pipelines with them."""
from __future__ import annotations

import itertools
import random as _random
from queue import Queue
from threading import Thread
from typing import Callable, Iterable

__all__ = ["map_readers", "buffered", "compose", "chain", "shuffle",
           "firstn", "xmap_readers", "cache", "multiprocess_reader"]


def map_readers(func: Callable, *readers):
    """Element-wise map over parallel readers (reference decorator.py:56)."""
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


def shuffle(reader, buf_size: int):
    """Buffered shuffle (reference decorator.py:106)."""
    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled


def chain(*readers):
    """Concatenate readers (reference decorator.py:146)."""
    def reader():
        for r in readers:
            yield from r()
    return reader


def compose(*readers, check_alignment: bool = True):
    """Zip readers into tuples, flattening tuple elements
    (reference decorator.py:198)."""
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        its = [r() for r in readers]
        for items in itertools.zip_longest(*its):
            if check_alignment and any(i is None for i in items):
                raise RuntimeError("compose: readers have different lengths")
            yield sum((make_tuple(i) for i in items), ())
    return reader


def buffered(reader, size: int):
    """Background-thread prefetch buffer (reference decorator.py:251 —
    python face of the C++ BufferedReader double-buffering)."""
    end = object()

    def buffered_reader():
        q: Queue = Queue(maxsize=size)

        def fill():
            try:
                for e in reader():
                    q.put(e)
            finally:
                q.put(end)

        t = Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                break
            yield e
    return buffered_reader


def firstn(reader, n: int):
    def reader_n():
        return itertools.islice(reader(), n)
    return reader_n


def cache(reader):
    """Materialize once, replay thereafter (reference decorator.py:33).
    The full stream is materialized on the FIRST call — a lazily filled
    cache would be corrupted by a partially consumed first epoch (the
    standard `break` out of a training loop)."""
    memory = []
    filled = [False]

    def cached():
        if not filled[0]:
            try:
                memory.extend(reader())
            except BaseException:
                memory.clear()  # a retried fill must not duplicate a prefix
                raise
            filled[0] = True
        yield from memory
    return cached


def xmap_readers(mapper: Callable, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map with worker threads (reference decorator.py:300). Thread
    workers (not processes): the mappers here are host-side preprocessing
    that releases the GIL in numpy, and device work stays in the main
    thread."""
    end = object()

    def xreader():
        in_q: Queue = Queue(buffer_size)
        out_q: Queue = Queue(buffer_size)

        def feed():
            for i, e in enumerate(reader()):
                in_q.put((i, e))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, e = item
                out_q.put((i, mapper(e)))

        Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            import heapq
            heap, want = [], 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                heapq.heappush(heap, item)
                while heap and heap[0][0] == want:
                    yield heapq.heappop(heap)[1]
                    want += 1
            while heap:
                yield heapq.heappop(heap)[1]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item[1]
    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Parity alias — thread-backed merge of multiple readers (true
    multiprocess handoff is the DataLoader's job on TPU hosts)."""
    return buffered(chain(*readers), queue_size)
