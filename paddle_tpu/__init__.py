"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built from scratch on JAX/XLA/Pallas/pjit.

Top-level namespace mirrors `paddle.*` (reference:
`/root/reference/python/paddle/__init__.py`): tensor ops, `nn`, `optimizer`,
`io`, `amp`, `jit`, `distributed`, `metric`, `profiler`, `vision`, `static`.
"""
from __future__ import annotations

import warnings as _warnings

# Make `JAX_PLATFORMS` binding before any backend initializes: accelerator
# plugins (axon) override the env var at registration, so a child spawned
# with `JAX_PLATFORMS=cpu` would otherwise still bind the real TPU — and
# hang forever when the chip is wedged (the round-3 bench failure).
from ._platform import pin_platform as _pin_platform  # noqa: E402
_pin_platform()

# TPU-first dtype policy: x64 stays off (int64 silently maps to int32 in XLA
# ops; TPU has no fast int64/float64 path). Silence the per-op truncation
# warning once here.
_warnings.filterwarnings(
    "ignore", message=".*requested in astype is not available.*")
_warnings.filterwarnings(
    "ignore", message=".*Explicitly requested dtype.*truncated.*")

from .framework.tensor import Tensor  # noqa: E402,F401
from .framework.param import Parameter  # noqa: E402,F401
from .framework import dtype as _dtype_mod  # noqa: E402
from .framework.dtype import (  # noqa: E402,F401
    bfloat16, bool_, complex128, complex64, float16, float32, float64,
    get_default_dtype, iinfo, finfo, int16, int32, int64, int8,
    set_default_dtype, uint8,
)
from .framework.place import (  # noqa: E402,F401
    CPUPlace, CUDAPlace, CustomPlace, TPUPlace, device_count, get_device,
    is_compiled_with_tpu, set_device,
)
from .framework.random import get_rng_state, seed, set_rng_state  # noqa: E402,F401
from .framework.tape import enable_grad, grad, no_grad  # noqa: E402,F401
from .framework.io import load, save  # noqa: E402,F401

from .ops import *  # noqa: E402,F401,F403
from .ops import linalg  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from . import hapi  # noqa: E402,F401
from .hapi import Model  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from .nn.initializer import ParamAttr  # noqa: E402,F401

from . import static  # noqa: E402,F401
from . import device  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from . import fault  # noqa: E402,F401
from .framework.flags import get_flags, set_flags  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from . import fft  # noqa: E402,F401
from . import signal  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import inference  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from . import reader  # noqa: E402,F401
from . import hub  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401
from . import onnx  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from . import cost_model  # noqa: E402,F401
from . import ops as tensor  # noqa: E402,F401  (paddle.tensor namespace)
from . import version  # noqa: E402,F401

# paddle-API conveniences
from .ops.creation import to_tensor  # noqa: E402,F401
from .framework.dtype import dtype  # noqa: E402,F401
# `paddle.bool` dtype alias is served by module __getattr__ (PEP 562) so
# the BUILTIN bool stays intact inside this module's own functions
def __getattr__(name):
    if name == "bool":
        return _dtype_mod.bool_
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
from .framework.place import CUDAPinnedPlace, NPUPlace  # noqa: E402,F401
from .ops.extras import batch  # noqa: E402,F401

# `paddle.callbacks` namespace alias (reference exposes hapi's callbacks at
# top level, `python/paddle/callbacks.py`); registered in sys.modules so
# `import paddle_tpu.callbacks` works, not just attribute access
from .hapi import callbacks  # noqa: E402,F401
import sys as _sys  # noqa: E402
_sys.modules[__name__ + ".callbacks"] = callbacks


def enable_static():
    """Switch to static-graph mode (reference `paddle.enable_static`)."""
    static._enable_static()


def disable_static():
    static._disable_static()


def in_dynamic_mode():
    return not static.in_static_mode()

DataParallel = None  # bound lazily by paddle_tpu.distributed import


def is_grad_enabled():
    from .framework import tape
    return tape.grad_enabled()


def set_grad_enabled(mode: bool):
    from .framework import tape
    st = tape._state()

    class _Ctx:
        def __init__(self):
            self.prev = st.grad_enabled
            st.grad_enabled = bool(mode)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            st.grad_enabled = self.prev
            return False
    return _Ctx()


def summary(net, input_size=None, dtypes=None, input=None):
    """Parameter count summary (hapi parity-lite)."""
    total = 0
    trainable = 0
    for _, p in net.named_parameters():
        n = p.size
        total += n
        if not p.stop_gradient:
            trainable += n
    info = {"total_params": total, "trainable_params": trainable}
    print(f"Total params: {total:,}\nTrainable params: {trainable:,}")
    return info


def flops(net, input_size, custom_ops=None, print_detail=False):
    return 0


__version__ = "0.1.0"
