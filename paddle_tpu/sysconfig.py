"""paddle.sysconfig parity (reference `python/paddle/sysconfig.py`):
include/lib dirs — here they point at the native component sources/builds
(`paddle_tpu/_native`), which is what a custom-op author links against."""
from __future__ import annotations

import os


def _pkg_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    return os.path.join(_pkg_dir(), "_native", "csrc")


def get_lib() -> str:
    return os.path.join(_pkg_dir(), "_native", "build")
