#!/usr/bin/env python
"""Headline benchmark: GPT-2 small (124M) LM training throughput, single chip.

Flagship config from BASELINE.json ("GPT-3 ... Fleet hybrid parallel" family,
scaled to one chip). Whole train step (fwd+bwd+Adam) is ONE XLA executable
(`paddle_tpu.jit.TrainStep`) — the TPU answer to the reference's
InterpreterCore hot loop (`/root/reference/paddle/fluid/framework/new_executor/`).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The reference publishes no in-repo numbers (BASELINE.json `published: {}`),
so vs_baseline is null; absolute tokens/sec/chip is the tracked metric.
"""
import json
import time

BATCH = 8
SEQ = 1024
WARMUP = 3
ITERS = 40  # long chain amortizes per-dispatch host/tunnel latency


def main():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.nn import functional as F

    paddle.seed(0)
    cfg = GPTConfig.gpt2_small()
    cfg.max_position_embeddings = SEQ
    cfg.dropout = 0.0
    cfg.attn_dropout = 0.0
    model = GPT(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                          weight_decay=0.01)

    def loss_fn(logits, labels):
        return F.cross_entropy(logits, labels)

    # O2 mixed precision: fp32 master weights + Adam state, bf16 compute —
    # the production TPU training configuration (no loss scaling needed)
    import jax.numpy as jnp
    step = TrainStep(model, loss_fn, opt, amp_dtype=jnp.bfloat16)

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (BATCH, SEQ)).astype("int32"))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (BATCH, SEQ)).astype("int32"))

    for _ in range(WARMUP):
        loss = step(ids, labels)
    float(loss)  # sync

    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = step(ids, labels)
    final_loss = float(loss)  # device sync
    dt = time.perf_counter() - t0

    tokens_per_s = BATCH * SEQ * ITERS / dt
    samples_per_s = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "gpt2-small-124M train tokens/sec/chip "
                  "(b8 x s1024, bf16 compute + fp32 master, fused step)",
        "value": round(tokens_per_s, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": None,
        "samples_per_sec_chip": round(samples_per_s, 3),
        "step_time_ms": round(1000 * dt / ITERS, 2),
        "final_loss": round(final_loss, 4),
        "note": "reference publishes no in-repo baseline (BASELINE.json published:{})",
    }))


if __name__ == "__main__":
    main()
