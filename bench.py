#!/usr/bin/env python
"""Headline benchmarks, single chip: GPT-2 small (flagship) + the BASELINE.md
target configs (ResNet-50 synthetic ImageNet, BERT-Base seq128).

Whole train step (fwd+bwd+optimizer) is ONE XLA executable
(`paddle_tpu.jit.TrainStep`) — the TPU answer to the reference's
InterpreterCore hot loop (`/root/reference/paddle/fluid/framework/new_executor/`).

Prints ONE JSON line: the flagship GPT-2 metric is `value`; the other
configs live in the same object under "configs", each with step time, MFU
(achieved FLOP/s from XLA cost_analysis over bf16 peak), and HBM bytes per
step. The reference publishes no in-repo numbers (BASELINE.json
`published: {}`), so vs_baseline is null; absolute numbers are tracked
round-over-round.

Measured attribution (--profile-steps) is ON by default so BENCH rounds
report xplane-measured device time, not just cost-model estimates; opt
out with --no-profile-steps. Each config also carries an `autotune` block
(kernel-autotuner cache events + tuned configs for that run) and the
GPT-2 config a `flops_accounting` block pinning down why hw_flops_util
can sit below mfu (Pallas custom-call flops are invisible to XLA
cost_analysis).
"""
import json
import os
import tempfile
import time

# the gpt2_decode tp_decode/disagg A/B blocks need >=2 devices; on the
# CPU bench box fake them via the host-platform device count. Must land
# in XLA_FLAGS before the first jax import anywhere in this process —
# inert on a real TPU backend (the flag only affects the host platform)
# and respects an operator-provided count.
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")

WARMUP = 3
ITERS = 40  # long chain amortizes per-dispatch host/tunnel latency

# --scale full|ci (env PADDLE_TPU_BENCH_SCALE): "full" is the TPU bench
# box configuration every BENCH round before r06 ran; "ci" shrinks the
# model/batch dims and iteration counts to what a CPU dev box can measure
# in minutes, WITHOUT changing what is measured (same models, same fused
# paths, same attribution/probe blocks). Scaled rounds record
# "scale": "ci" per config + round so the gate and readers can never
# mistake them for full-scale numbers.
_SCALE = os.environ.get("PADDLE_TPU_BENCH_SCALE", "full")


def _scaled(full, ci):
    return ci if _SCALE == "ci" else full

# --profile-steps N: after each config's timed run, capture N extra steps
# in a jax.profiler session (profiler/xplane.py) so the BENCH JSON reports
# MEASURED device time (device_src="xplane") next to the cost-model
# estimates, per config and per eager op. DEFAULT ON for BENCH rounds
# (ROADMAP item 1c: r06+ reports measured, not cost-model, attribution) —
# opt out with --no-profile-steps / --profile-steps 0 /
# PADDLE_TPU_BENCH_PROFILE_STEPS=0.
try:
    DEFAULT_PROFILE_STEPS = int(os.environ.get(
        "PADDLE_TPU_BENCH_PROFILE_STEPS", "3"))
except ValueError:  # malformed env must degrade, never kill the round
    DEFAULT_PROFILE_STEPS = 3
_PROFILE_STEPS = 0
_PROFILE_RESULTS = {}

# one metric, one definition (ROADMAP item 1a, VERDICT r5 "hw_flops_util
# 0.42 < MFU 0.485 is odd"): `mfu` — analytic model FLOPs (6*N*tokens +
# attention term) over peak — is THE headline utilization metric.
# `hw_flops_util` divides XLA cost_analysis flops by peak, and
# cost_analysis CANNOT see into Pallas custom calls: with the fused
# flash-attention path active, the attention fwd+bwd flops (~13% of GPT-2
# model flops at s1024) simply vanish from the numerator, which is exactly
# the r05 0.42-vs-0.485 gap. `flops_accounting` in each affected config
# shows both numerators and `hw_flops_util_incl_pallas` (cost-analysis
# flops + analytic flops of the active Pallas kernels) for the
# apples-to-apples comparison.
FLOPS_NOTE = ("mfu (analytic model FLOPs / peak) is the headline "
              "utilization metric; hw_flops_util uses XLA cost-analysis "
              "flops, which exclude Pallas custom-call kernels (flash "
              "attention) — hw_flops_util < mfu whenever the fused "
              "kernels are active, not a perf regression. "
              "hw_flops_util_incl_pallas adds the analytic kernel flops "
              "back to the cost-analysis count.")


def _profile_root() -> str:
    return os.environ.get(
        "PADDLE_TPU_PROFILE_DIR",
        os.path.join(tempfile.gettempdir(), f"bench_profile_{os.getpid()}"))

# hbm_gb_per_step / hw_flops_util provenance (VERDICT r5 Weak #6): they come
# from compiled.cost_analysis(), not hardware counters — say so in the JSON
ESTIMATES_NOTE = ("hbm_gb_per_step and hw_flops_util are XLA cost-analysis "
                  "ESTIMATES (upper bound, cache-oblivious), not measured "
                  "hardware counters")

# bf16 peak of one v5e chip; override for other parts (v4: 275e12, v5p: 459e12)
PEAK_FLOPS = float(os.environ.get("BENCH_PEAK_FLOPS", 197e12))

_INIT_HUNG = False  # set when the backend-init probe timed out (see main)

# step-window records (profiler/monitor.py schema) from every timed run this
# process executed; folded into the output under observability.step_records
_STEP_RECORDS = []

# sentinel-overhead measurement (health on vs off on the GPT-2 config);
# folded into the output under observability.health
_HEALTH_BLOCK = {}


def health_overhead_probe(make_step, batch, iters=10, warmup=2):
    """Measure the in-graph health sentinel's step-wall overhead.

    `make_step(health: bool)` builds a fresh TrainStep for the same model;
    both variants are timed through `TrainStep.__call__` (so both pay the
    identical Python dispatch) for `iters` steps. The health=True loop
    pays the sentinel's real production cost: the in-graph reductions plus
    one tiny per-step device->host fetch. Returns the bench
    `observability.health` block (validated by tools/check_bench_result)."""
    from paddle_tpu.profiler import health as _health
    times = {}
    probe = None
    for label, on in (("off", False), ("on", True)):
        step = make_step(on)
        if on:
            probe = step._health_probe
        loss = None
        for _ in range(warmup):
            loss = step(*batch)
        if loss is not None:
            # drain async warmup dispatches BEFORE opening the window —
            # their device tail would inflate both measurements and
            # deflate the relative overhead the acceptance gate reads
            float(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(*batch)
        float(loss)  # device sync closes the timed window
        times[label] = 1000.0 * (time.perf_counter() - t0) / iters
    off, on = times["off"], times["on"]
    stats = _health.last_stats() or {}
    sentinel = {
        "loss": _finite_or_none(stats.get("loss")),
        "grad_norm": _finite_or_none(stats.get("grad_norm")),
        "update_ratio": _finite_or_none(stats.get("update_ratio")),
        "nonfinite": bool(stats.get("nonfinite", False)),
    }
    return {
        "step_ms_off": round(off, 3),
        "step_ms_on": round(on, 3),
        "overhead_frac": round((on - off) / off, 4) if off > 0 else None,
        "interval": _health.interval(),
        "groups": len(probe.group_names) if probe is not None else None,
        "sentinel": sentinel,
        "note": ("health on/off timed through TrainStep.__call__ on the "
                 "same model; 'on' includes the in-graph sentinel "
                 "reductions and the per-step stats-vector fetch"),
    }


def _finite_or_none(v):
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if v == v and v not in (float("inf"), float("-inf")) else None


def _observability_snapshot():
    """Metrics-registry snapshot + retrace summary + step records +
    compile attribution + device-vs-host split + recent structured events,
    folded into the bench JSON so each round's perf line carries its own
    observability data (PR 2, extended in the fleet-observability PR).
    Never raises — the bench must stay unkillable."""
    out = {}
    try:
        from paddle_tpu.profiler import metrics as _metrics
        _metrics.update_device_memory_gauges()
        out["metrics"] = _metrics.default_registry().snapshot()
    except Exception as e:
        out["metrics_error"] = f"{type(e).__name__}: {e}"
    try:
        from paddle_tpu.profiler.watchdog import get_watchdog
        wd = get_watchdog()
        out["retraces_total"] = wd.total_retraces()
        out["retrace_events"] = [e.to_dict() for e in list(wd.events)[-10:]]
    except Exception as e:
        out["retrace_error"] = f"{type(e).__name__}: {e}"
    try:
        # XLA compile cost per entry point (jax.monitoring feed): the
        # relaunch/cold-start story in numbers
        from paddle_tpu.profiler import compile_watch
        out["compile_attribution"] = compile_watch.summary()
    except Exception as e:
        out["compile_error"] = f"{type(e).__name__}: {e}"
    try:
        out["device_time"] = _device_time_probe()
    except Exception as e:
        out["device_time_error"] = f"{type(e).__name__}: {e}"
    if _HEALTH_BLOCK:
        out["health"] = dict(_HEALTH_BLOCK)
    try:
        from paddle_tpu.ops.pallas import autotune as _at
        out["autotune"] = _at.summary()
    except Exception as e:
        out["autotune_error"] = f"{type(e).__name__}: {e}"
    try:
        from paddle_tpu.profiler import events as _events
        out["events_tail"] = _events.recent(20)
    except Exception as e:
        out["events_error"] = f"{type(e).__name__}: {e}"
    out["step_records"] = list(_STEP_RECORDS)[-10:]
    return out


def _device_time_probe():
    """Per-op host-dispatch vs device-execution split on a handful of
    representative eager ops (profiler/device_time.py). On CPU (and by
    default on TPU) device times are roofline ESTIMATES from the cost
    model and labeled so; `PADDLE_TPU_DEVICE_TIME=sync` measures real
    completion at the price of serialized dispatch; under --profile-steps
    the probe runs inside an xplane capture session, so rows carry
    MEASURED trace-correlated device time (src="xplane") and the
    correlation block reports the measured-vs-estimate delta per op."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.profiler import device_time
    from paddle_tpu.profiler.recorder import get_recorder

    rng = np.random.default_rng(0)
    a = paddle.to_tensor(rng.normal(size=(256, 256)).astype("float32"))
    b = paddle.to_tensor(rng.normal(size=(256, 256)).astype("float32"))

    def run_ops():
        for _ in range(3):  # first pass compiles; later passes are steady
            c = paddle.matmul(a, b)
            d = paddle.nn.functional.softmax(c)
            (d + c).mean()

    correlation = None
    if _PROFILE_STEPS > 0:
        from paddle_tpu.profiler import xplane
        sess = xplane.CaptureSession(
            os.path.join(_profile_root(), "eager_probe"))
        sess.start()
        try:
            run_ops()
        finally:
            summary = sess.stop(steps=3)
        rows = summary["device_time"]["rows"]
        correlation = summary.get("correlation")
    else:
        rec = get_recorder()
        was = rec.enabled
        rec.clear()
        rec.enabled = True
        try:
            run_ops()
        finally:
            rec.enabled = was
        rows = device_time.split_rows(rec.collect())
    platform, peak_flops, peak_bw = device_time.platform_peaks()
    mode = ("xplane" if any(r.get("src") == "xplane" for r in rows)
            else "measured" if device_time.sync_mode() else "estimate")
    out = {
        "rows": rows,
        "mode": mode,
        "platform": platform,
        "note": ("host_ms is dispatch latency; device_ms is roofline-"
                 "estimated from cost-model flops/bytes at peaks "
                 f"({peak_flops:.3g} FLOP/s, {peak_bw:.3g} B/s) unless "
                 "mode=measured (PADDLE_TPU_DEVICE_TIME=sync) or "
                 "mode=xplane (--profile-steps trace correlation)"),
    }
    if correlation is not None:
        out["correlation"] = correlation
    return out


def _profile_compiled_steps(label, run_step, flops_per_step):
    """Capture `_PROFILE_STEPS` invocations of an already-compiled train
    step in a jax.profiler session: each step runs inside a
    `RecordEvent("train_step")` span (synced before the span closes), so
    xplane correlation yields the MEASURED per-step device lane-time next
    to the cost-model estimate. Stores a compact result under
    `_PROFILE_RESULTS[label]`; never raises (the bench must finish)."""
    from paddle_tpu.profiler import xplane
    from paddle_tpu.profiler.utils import RecordEvent
    try:
        sess = xplane.CaptureSession(os.path.join(_profile_root(), label))
        sess.start()
        try:
            for _ in range(_PROFILE_STEPS):
                with RecordEvent("train_step"):
                    run_step()  # syncs internally: device work stays in-span
        finally:
            summary = sess.stop(steps=_PROFILE_STEPS)
        rows = [r for r in summary["device_time"]["rows"]
                if r["op"] == "train_step"]
        measured_ms = rows[0]["device_ms"] / _PROFILE_STEPS if rows else None
        est_ms = (1000.0 * flops_per_step / PEAK_FLOPS) \
            if flops_per_step else None
        _PROFILE_RESULTS[label] = {
            # measured per-segment attribution (attention fwd/bwd, mlp,
            # ln, loss/CE, optimizer, ...) classified from the trace's
            # XLA op metadata — profiler/xplane.segment_breakdown
            "segments": summary.get("segments"),
            "session_dir": summary["session_dir"],
            "status": summary["status"],
            "steps": _PROFILE_STEPS,
            "device_ms_per_step_measured": (round(measured_ms, 3)
                                            if measured_ms else None),
            "device_ms_per_step_cost_model": (round(est_ms, 3)
                                              if est_ms else None),
            "measured_vs_estimate": (round(measured_ms / est_ms, 3)
                                     if measured_ms and est_ms else None),
            "device_src": rows[0]["src"] if rows else None,
            "correlation": summary.get("correlation"),
            "note": ("device_ms_per_step_measured is xplane-trace work-lane "
                     "time per compiled step; cost_model row is the XLA "
                     "cost-analysis FLOPs at the configured peak"),
        }
    except Exception as e:
        _PROFILE_RESULTS[label] = {"error": f"{type(e).__name__}: {e}"}


def _run_config(step, args, iters=None, warmup=None,
                profile_label=None):
    """AOT-compile the TrainStep ONCE, read cost_analysis from the same
    executable, and time by invoking it directly (no second jit compile).

    Returns (sec_per_step, final_loss, flops, bytes_accessed). With
    --profile-steps and a `profile_label`, a bounded xplane capture of the
    same executable follows the timed loop (measured device time per
    config in the JSON)."""
    import jax.numpy as jnp
    from paddle_tpu.framework import random as random_mod

    if iters is None:
        iters = _scaled(ITERS, 8)
    if warmup is None:
        warmup = _scaled(WARMUP, 1)
    rng = random_mod.default_generator().split()
    lr = jnp.asarray(step.optimizer.get_lr(), jnp.float32)
    arrs = [a.data for a in args]
    compiled = step._step.lower(step.params, step.buffers, step.opt_state,
                                rng, lr, 1, *arrs).compile()
    flops = nbytes = None
    try:
        an = compiled.cost_analysis()
        if isinstance(an, list):
            an = an[0]
        flops, nbytes = an.get("flops"), an.get("bytes accessed")
    except Exception:
        pass
    params, buffers, opt_state = step.params, step.buffers, step.opt_state
    # t is a traced scalar arg of the lowered executable: thread the real
    # step counter so Adam/AdamW bias correction follows a genuine
    # trajectory instead of freezing at t=1 (ADVICE r2)
    t = 0
    for _ in range(warmup):
        t += 1
        # [:4] tolerates the health-armed step's extra sentinel output
        # (PADDLE_TPU_HEALTH=1 while benching)
        loss, params, buffers, opt_state = compiled(
            params, buffers, opt_state, rng, lr, t, *arrs)[:4]
    float(loss)  # sync
    try:
        from paddle_tpu.profiler.watchdog import get_watchdog
        retrace0 = get_watchdog().total_retraces()
    except Exception:
        retrace0 = None
    try:
        from paddle_tpu.profiler import server as _obs_server
    except Exception:
        _obs_server = None
    t0 = time.perf_counter()
    for _ in range(iters):
        t += 1
        loss, params, buffers, opt_state = compiled(
            params, buffers, opt_state, rng, lr, t, *arrs)[:4]
        if _obs_server is not None:
            _obs_server.note_step(t)  # /healthz liveness while benching
    final_loss = float(loss)  # device sync
    dt = time.perf_counter() - t0
    # one step-window observability record per timed run (PR 2 schema)
    try:
        from paddle_tpu.profiler.monitor import make_step_record
        from paddle_tpu.profiler.watchdog import get_watchdog
        batch = (int(arrs[0].shape[0])
                 if arrs and getattr(arrs[0], "ndim", 0) else None)
        _STEP_RECORDS.append(make_step_record(
            step=iters, window_steps=iters, window_time_s=dt,
            samples=batch * iters if batch else None,
            flops_per_step=flops, peak_flops=PEAK_FLOPS,
            retraces=(get_watchdog().total_retraces() - retrace0
                      if retrace0 is not None else 0)))
    except Exception:
        pass
    if profile_label and _PROFILE_STEPS > 0:
        state = {"t": t, "params": params, "buffers": buffers,
                 "opt_state": opt_state}

        def run_step():
            state["t"] += 1
            loss, state["params"], state["buffers"], state["opt_state"] = \
                compiled(state["params"], state["buffers"],
                         state["opt_state"], rng, lr, state["t"], *arrs)[:4]
            float(loss)  # sync inside the caller's RecordEvent span
        _profile_compiled_steps(profile_label, run_step, flops)
    return dt / iters, final_loss, flops, nbytes


def _platform() -> str:
    """Backend platform recorded per config and round so the gate can
    refuse cross-platform throughput comparisons (a CPU dev-box round vs
    a TPU driver round is not a regression, it is incomparable)."""
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:
        return "unknown"


def _program_audit_block(reports_fn):
    """Static program audit of this config's compiled executables
    (paddle_tpu.analysis: trace + lower only, nothing runs) — aggregate
    counts + the findings themselves, so a bench round records whether
    the headline programs are hazard-clean on the box that produced the
    numbers. `reports_fn` -> list[AuditReport]. Never raises."""
    try:
        reports = reports_fn()
        counts = {"info": 0, "low": 0, "medium": 0, "high": 0}
        for r in reports:
            for sev, n in r.counts().items():
                counts[sev] += n
        return {
            "counts": counts,
            "clean_high": counts["high"] == 0,
            "reports": [r.to_dict(max_findings=8) for r in reports],
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def _tuned_vs_static_probe(build_step, args, iters=6, warmup=2):
    """Autotune tuned-vs-static comparison, measured in-round: one short
    timed window with the tuner in its current mode, one with the
    PADDLE_TPU_AUTOTUNE=0 kill switch (the pre-autotune static picks,
    fresh trace so block resolution actually re-runs). On TPU this is the
    `tuned >= static` acceptance check; on CPU both sides resolve static
    and the ratio reads ~1. Never raises."""
    import os as _os

    def timed():
        step = build_step()
        sec, _, _, _ = _run_config(step, args, iters=iters, warmup=warmup)
        return 1000.0 * sec

    try:
        from paddle_tpu.ops.pallas import autotune as _at
        mode = _at.mode()
        t_cur = timed()
        prev = _os.environ.get("PADDLE_TPU_AUTOTUNE")
        _os.environ["PADDLE_TPU_AUTOTUNE"] = "0"
        try:
            t_static = timed()
        finally:
            if prev is None:
                _os.environ.pop("PADDLE_TPU_AUTOTUNE", None)
            else:
                _os.environ["PADDLE_TPU_AUTOTUNE"] = prev
        return {
            "mode": mode,
            "probe_ms_tuned": round(t_cur, 2),
            "probe_ms_static": round(t_static, 2),
            "tuned_speedup_vs_static": (round(t_static / t_cur, 3)
                                        if t_cur > 0 else None),
            "note": ("probe-vs-probe, fresh TrainStep each side; "
                     "'tuned' side uses the live autotune mode (static "
                     "resolution off-TPU), 'static' forces the "
                     "kill-switch picks"),
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"}


def bench_gpt2():
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.nn import functional as F

    B, L = _scaled((8, 1024), (2, 256))
    paddle.seed(0)
    cfg = GPTConfig.gpt2_small()
    cfg.max_position_embeddings = L
    cfg.dropout = 0.0
    cfg.attn_dropout = 0.0
    model = GPT(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                          weight_decay=0.01)
    # O2 mixed precision: fp32 master weights + Adam state, bf16 compute —
    # the production TPU training configuration (no loss scaling needed)
    step = TrainStep(model, F.cross_entropy, opt, amp_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (B, L)).astype("int32"))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (B, L)).astype("int32"))
    from paddle_tpu.ops.pallas import flash_attention as _fa
    fa_pallas0 = _fa._stats["pallas"]
    sec, loss, flops, nbytes = _run_config(step, (ids, labels),
                                           profile_label="gpt2_small")
    # did this config's trace actually take the fused Pallas attention
    # path? (decides whether its flops are missing from cost_analysis)
    fa_pallas = _fa._stats["pallas"] > fa_pallas0
    # sentinel overhead (ISSUE 10 acceptance: <=2% step wall on this
    # config): same model, health on vs off, short __call__-timed loops
    try:
        def mk(health):
            o = optimizer.AdamW(learning_rate=1e-4,
                                parameters=model.parameters(),
                                weight_decay=0.01)
            return TrainStep(model, F.cross_entropy, o,
                             amp_dtype=jnp.bfloat16, health=health)
        _HEALTH_BLOCK.update(health_overhead_probe(
            mk, (ids, labels), iters=_scaled(10, 4),
            warmup=_scaled(2, 1)))
    except Exception as e:
        _HEALTH_BLOCK.update({"error": f"{type(e).__name__}: {e}"})
    # autotune tuned-vs-static, measured on THIS config's shapes
    def _mk_step():
        o = optimizer.AdamW(learning_rate=1e-4,
                            parameters=model.parameters(),
                            weight_decay=0.01)
        return TrainStep(model, F.cross_entropy, o, amp_dtype=jnp.bfloat16)
    tuned_vs_static = _tuned_vs_static_probe(
        _mk_step, (ids, labels), iters=_scaled(6, 3), warmup=1)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # model-FLOPs MFU: 6*N per token (fwd+bwd) + attention 12*L*D_model*T
    attn_flops = 12 * cfg.num_layers * B * L * L * cfg.hidden_size
    model_flops = 6 * n_params * B * L + attn_flops
    pallas_flops = attn_flops if fa_pallas else 0
    return {
        "name": f"gpt2-small-124M b{B} s{L} bf16+fp32-master",
        "platform": _platform(),
        "scale": _SCALE,
        "fused_opt": bool(getattr(step, "fused_opt", False)),
        "tuned_vs_static": tuned_vs_static,
        "program_audit": _program_audit_block(
            lambda: [step.audit(ids, labels)]),
        "tokens_per_sec_chip": round(B * L / sec, 1),
        "samples_per_sec_chip": round(B / sec, 3),
        "step_time_ms": round(1000 * sec, 2),
        "final_loss": round(loss, 4),
        "mfu": round(model_flops / sec / PEAK_FLOPS, 4),
        "hw_flops_util": (round(flops / sec / PEAK_FLOPS, 4)
                          if flops else None),
        "flops_accounting": {
            "model_flops_per_step": model_flops,
            "xla_cost_flops_per_step": flops,
            "pallas_attn_flops_per_step": pallas_flops,
            "hw_flops_util_incl_pallas": (
                round((flops + pallas_flops) / sec / PEAK_FLOPS, 4)
                if flops else None),
            "note": FLOPS_NOTE,
        },
        "hbm_gb_per_step": round(nbytes / 1e9, 2) if nbytes else None,
        "estimates_note": ESTIMATES_NOTE,
    }


def _conv_fusion_micro_ab(B=128, dtype_bytes=2):
    """Per-shape HBM-bytes accounting for the fused conv+BN chain on the
    ResNet-50 bottleneck 1x1 tails — the `flops_accounting` pattern
    applied to bytes: the COMPOSED side is measured from XLA
    cost_analysis of the matmul+stats+normalize chain (custom-call-free,
    so the estimate sees every pass, including the statistics read the
    fusion eliminates); the FUSED side is the kernel's analytic traffic
    (read x+w, write y + two (C,) stat vectors, then the elementwise
    apply's read y / write out) — cost_analysis cannot see inside Pallas
    custom calls, which is exactly why the composed/analytic pairing is
    the honest comparison. Never raises."""
    import jax
    import jax.numpy as jnp

    # (hw, Cin, Cout) of the bottleneck conv3 tails, ResNet-50 at 224px
    shapes = [(56, 64, 256), (28, 128, 512), (14, 256, 1024),
              (7, 512, 2048)]
    dt = jnp.bfloat16 if dtype_bytes == 2 else jnp.float32
    rows, tot_comp, tot_fused = [], 0, 0
    for hw_, cin, cout in shapes:
        try:
            R = B * hw_ * hw_

            def chain(x, w, g, b):
                y = jnp.dot(x, w, preferred_element_type=jnp.float32) \
                    .astype(dt)
                mean = jnp.mean(y, axis=0, dtype=jnp.float32)
                var = jnp.mean(
                    jnp.square(y.astype(jnp.float32)), axis=0) - mean ** 2
                out = (y.astype(jnp.float32) - mean) \
                    * jax.lax.rsqrt(var + 1e-5) * g + b
                return jnp.maximum(out, 0.0).astype(dt)

            args = (jax.ShapeDtypeStruct((R, cin), dt),
                    jax.ShapeDtypeStruct((cin, cout), dt),
                    jax.ShapeDtypeStruct((cout,), jnp.float32),
                    jax.ShapeDtypeStruct((cout,), jnp.float32))
            an = jax.jit(chain).lower(*args).compile().cost_analysis()
            if isinstance(an, list):
                an = an[0]
            composed = an.get("bytes accessed")
            # fused: conv kernel reads x + w, writes y + 2x(C,) f32 sums;
            # apply kernel reads y (+ per-channel consts), writes out
            fused = (R * cin + cin * cout + 2 * R * cout) * dtype_bytes \
                + (R * cout) * dtype_bytes + 10 * cout * 4
            # minimum-pass roofline of the composed chain (perfect XLA
            # fusion assumed): fused + the one full statistics read of y
            # the epilogue fusion eliminates — savings are computed vs
            # THIS conservative model; the raw cost-analysis column
            # (cache-oblivious, counts unfused elementwise passes) is
            # kept as context, not as the denominator
            composed_model = fused + R * cout * dtype_bytes
            if composed:
                rows.append({
                    "shape": f"b{B}x{hw_}x{hw_} {cin}->{cout}",
                    "composed_gb_cost_analysis": round(composed / 1e9, 3),
                    "composed_gb_model": round(composed_model / 1e9, 3),
                    "fused_gb_model": round(fused / 1e9, 3),
                    "pct_saved": round(
                        100 * (1 - fused / composed_model), 1),
                })
                tot_comp += composed_model
                tot_fused += fused
        except Exception:
            continue
    out = {"rows": rows, "note": (
        "fused side: analytic kernel traffic (stats computed in the conv "
        "epilogue — no separate full-activation statistics read); "
        "composed_gb_model: the same + that one statistics read "
        "(minimum-pass roofline, perfect-fusion assumption); pct_saved "
        "is fused vs composed_gb_model (conservative); "
        "composed_gb_cost_analysis is XLA's cache-oblivious estimate of "
        "the custom-call-free chain, kept as context")}
    if tot_comp:
        out["total_pct_saved"] = round(100 * (1 - tot_fused / tot_comp), 1)
    return out


def _paged_vs_dense_ab(model, ctxs, page_size, n_tokens=8, dense_iters=3):
    """Per-token decode cost, paged vs cacheless, at growing context.

    Paged side: ONE ServingEngine (one compiled decode executable over a
    fixed page-pool shape) decodes `n_tokens` after prefilling a
    `ctx`-token prompt — per-token wall from the engine's decode-phase
    clock (prefill + compiles excluded). Dense side: one jitted FULL
    forward over the `ctx`-token sequence (what a cacheless decoder pays
    for every token at that context), timed after its own warmup. The
    acceptance read: paged stays ~flat as ctx grows, dense grows with
    it. Never raises."""
    import jax
    import numpy as np
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.jit import functionalize

    rng = np.random.default_rng(7)
    vocab = model.cfg.vocab_size
    max_len = max(ctxs) + n_tokens + 1
    eng = ServingEngine(model, max_batch=1, max_len=max_len,
                        page_size=page_size, name="paged_ab")
    # warm the decode executable (and one prefill bucket) out of the clock
    eng.submit(rng.integers(1, vocab, (8,)).tolist(), max_new_tokens=2)
    eng.run_until_idle()
    apply_fn, params, buffers = functionalize(model)
    dense_jit = jax.jit(lambda p, b, x: apply_fn(p, b, None, x)[0])
    rows = []
    for ctx in ctxs:
        prompt = rng.integers(1, vocab, (ctx,)).tolist()
        w0, t0 = eng.stats["decode_wall_s"], eng.stats["decode_tokens"]
        eng.submit(prompt, max_new_tokens=n_tokens)
        eng.run_until_idle()
        dw = eng.stats["decode_wall_s"] - w0
        dt = eng.stats["decode_tokens"] - t0
        paged_ms = 1000.0 * dw / max(dt, 1)
        import jax.numpy as jnp
        jnp_ids = jnp.asarray(np.asarray([prompt], np.int32))
        jax.block_until_ready(dense_jit(params, buffers, jnp_ids))  # compile
        td = time.perf_counter()
        for _ in range(dense_iters):
            jax.block_until_ready(dense_jit(params, buffers, jnp_ids))
        dense_ms = 1000.0 * (time.perf_counter() - td) / dense_iters
        rows.append({"ctx": int(ctx),
                     "paged_ms_per_token": round(paged_ms, 3),
                     "dense_ms_per_token": round(dense_ms, 3)})
    out = {"rows": rows, "decode_tokens_per_ctx": n_tokens,
           "note": ("paged: one fixed decode executable over the page "
                    "pool, per-token wall at the given prefilled "
                    "context; dense: one jitted full forward over the "
                    "ctx-token sequence = the cacheless cost of ONE "
                    "token at that context")}
    if len(rows) >= 2 and rows[0]["paged_ms_per_token"] > 0 \
            and rows[0]["dense_ms_per_token"] > 0:
        out["paged_growth"] = round(rows[-1]["paged_ms_per_token"]
                                    / rows[0]["paged_ms_per_token"], 3)
        out["dense_growth"] = round(rows[-1]["dense_ms_per_token"]
                                    / rows[0]["dense_ms_per_token"], 3)
        if rows[-1]["paged_ms_per_token"] > 0:
            out["speedup_at_max_ctx"] = round(
                rows[-1]["dense_ms_per_token"]
                / rows[-1]["paged_ms_per_token"], 3)
    return out


def _fused_vs_eager_ab(model, prompts, max_batch, max_len, page_size,
                       n_tokens):
    """The serving-v2 headline A/B: the SAME greedy traffic through the
    single-dispatch fused decode step vs the per-op eager path (identical
    math — the engines must produce identical tokens), per-token decode
    wall from each engine's own stats."""
    from paddle_tpu.inference.serving import ServingEngine

    out = {"decode_tokens_per_mode": len(prompts) * n_tokens}
    tokens = {}
    for mode in ("fused", "eager"):
        eng = ServingEngine(model, max_batch=max_batch, max_len=max_len,
                            page_size=page_size, name=f"ab_{mode}",
                            decode_mode=mode)
        # warm compile/trace out of the clock (the eager path traces
        # per-op abstract evals on first use too)
        eng.submit(prompts[0][:4] or [1], max_new_tokens=2)
        eng.run_until_idle()
        w0, t0 = eng.stats["decode_wall_s"], eng.stats["decode_tokens"]
        reqs = [eng.submit(p, max_new_tokens=n_tokens) for p in prompts]
        eng.run_until_idle()
        dw = eng.stats["decode_wall_s"] - w0
        dt = eng.stats["decode_tokens"] - t0
        out[f"{mode}_ms_per_token"] = round(1000.0 * dw / max(dt, 1), 3)
        tokens[mode] = [r.result(5) for r in reqs]
    out["identical_tokens"] = tokens["fused"] == tokens["eager"]
    if out["eager_ms_per_token"] and out["fused_ms_per_token"]:
        out["speedup"] = round(out["eager_ms_per_token"]
                               / out["fused_ms_per_token"], 3)
    out["note"] = ("same greedy prompts through decode_mode=fused (ONE "
                   "donated executable per lane bucket) vs eager (per-op "
                   "dispatch of the identical step fn); "
                   "identical_tokens is the bit-parity check")
    return out


def _shared_prefix_ab(model, max_batch, max_len, page_size, n_requests,
                      prefix_len, n_tokens):
    """Copy-on-write shared-prefix A/B: the parallel-sampling shape —
    n_requests with the IDENTICAL prompt and distinct sampling seeds,
    admitted with prefix sharing on vs off. The win is PAGE-POOL
    OCCUPANCY (the on side's free-page watermark stays high because the
    prompt KV is resident once and forked), and the prompt length is
    deliberately NOT page-aligned so every sharer's first divergent
    decode write lands on the shared tail page and exercises the
    copy-on-write fork (cow_copies)."""
    import numpy as np
    from paddle_tpu.inference.serving import SamplingParams, ServingEngine

    rng = np.random.default_rng(3)
    vocab = model.cfg.vocab_size
    if prefix_len % page_size == 0:
        prefix_len -= 2  # keep a partial tail page (see docstring)
    common = rng.integers(1, vocab, (prefix_len,)).tolist()
    out = {"requests": n_requests, "prefix_tokens": prefix_len}
    for label, share in (("on", True), ("off", False)):
        eng = ServingEngine(model, max_batch=max_batch, max_len=max_len,
                            page_size=page_size, name=f"shp_{label}",
                            share_prefix=share)
        reqs = [eng.submit(common, max_new_tokens=n_tokens,
                           sampling=SamplingParams(temperature=0.8,
                                                   seed=1000 + i))
                for i in range(n_requests)]
        eng.run_until_idle()
        for r in reqs:
            r.result(5)
        st = eng.stats
        out[label] = {
            "min_free_pages": int(st["min_free_pages"]),
            "prefix_hit_tokens": int(st["prefix_hit_tokens"]),
            "shared_admissions": int(st["shared_admissions"]),
            "cow_copies": int(st["cow_copies"]),
            "preemptions": int(st["preemptions"]),
            "completed": int(st["completed"]),
        }
        leak = eng.allocator.outstanding()
        out[label]["leaked_pages"] = len(leak)
    out["pages_saved_at_watermark"] = (out["on"]["min_free_pages"]
                                       - out["off"]["min_free_pages"])
    out["note"] = ("identical prompt x n_requests with distinct sampling "
                   "seeds (parallel sampling), shared-prefix CoW admission "
                   "on vs off; pages_saved_at_watermark = extra free pages "
                   "at the deepest point = extra admission headroom; "
                   "cow_copies counts divergent-write page forks")
    return out


def _tp_decode_ab(model, prompts, max_batch, max_len, page_size,
                  n_tokens):
    """Tensor-parallel decode A/B: the SAME greedy traffic through the
    single-chip fused engine vs a 2-way ``Mesh(("tp",))`` engine (paged
    KV pools + attention heads sharded over the head axis, block tables
    host-side). The claim is capacity, not speed — per-device KV bytes
    halve at the same TPOT — so the gate pins `identical_tokens` (TP is
    a layout change, never a math change) and reports the per-link
    collective bytes of the sharded decode program."""
    import jax
    import numpy as np
    from jax.sharding import Mesh
    from paddle_tpu.inference.serving import ServingEngine

    if len(jax.devices()) < 2:
        return {"skipped": "needs >=2 devices"}
    out = {"decode_tokens_per_mode": len(prompts) * n_tokens}
    tokens = {}
    for mode in ("single", "tp"):
        mesh = (Mesh(np.array(jax.devices()[:2]), ("tp",))
                if mode == "tp" else None)
        eng = ServingEngine(model, max_batch=max_batch, max_len=max_len,
                            page_size=page_size, name=f"tpab_{mode}",
                            mesh=mesh)
        eng.submit(prompts[0][:4] or [1], max_new_tokens=2)  # warm
        eng.run_until_idle()
        w0, t0 = eng.stats["decode_wall_s"], eng.stats["decode_tokens"]
        reqs = [eng.submit(p, max_new_tokens=n_tokens) for p in prompts]
        eng.run_until_idle()
        dw = eng.stats["decode_wall_s"] - w0
        dt = eng.stats["decode_tokens"] - t0
        out[f"{mode}_ms_per_token"] = round(1000.0 * dw / max(dt, 1), 3)
        tokens[mode] = [r.result(5) for r in reqs]
        if mode == "tp":
            out["tp_degree"] = eng.tp_degree()
            try:
                link = eng.audit(emit=False)[-1]
                out["collective_bytes_by_link"] = dict(link.link_bytes)
            except Exception as e:
                out["collective_bytes_by_link"] = {
                    "error": f"{type(e).__name__}: {e}"}
    out["identical_tokens"] = tokens["single"] == tokens["tp"]
    if out["single_ms_per_token"] and out["tp_ms_per_token"]:
        out["tpot_ratio"] = round(out["tp_ms_per_token"]
                                  / out["single_ms_per_token"], 3)
    out["note"] = ("same greedy prompts through the single-chip fused "
                   "engine vs the head-sharded 2-way TP mesh engine; "
                   "identical_tokens is the bit-parity check, tpot_ratio "
                   "~1.0 means the model could be tp_degree x larger at "
                   "the same TPOT (per-device KV bytes / tp_degree)")
    return out


def _disagg_ab(model, prompts, max_batch, max_len, page_size, n_tokens):
    """Disaggregated prefill/decode A/B: the SAME greedy traffic through
    the co-located engine vs the two-stage pipeline (prefill workers on
    their own devices producing KV pages into the handoff queue, the
    decode engine draining it inside its own step). The claim is
    interference isolation — decode TPOT stops paying for prefill
    bubbles — pinned again by `identical_tokens` (the handoff is a page
    move, never a math change) plus the handoff-plane counters."""
    from paddle_tpu.inference.disagg import DisaggPipeline
    from paddle_tpu.inference.serving import ServingEngine

    out = {"decode_tokens_per_mode": len(prompts) * n_tokens}
    tokens = {}
    for mode in ("colocated", "disagg"):
        eng = ServingEngine(model, max_batch=max_batch, max_len=max_len,
                            page_size=page_size, name=f"dab_{mode}")
        pipe = DisaggPipeline(eng, num_workers=1) if mode == "disagg" \
            else None
        submit = pipe.submit if pipe is not None else eng.submit
        drain = (pipe.run_until_idle if pipe is not None
                 else eng.run_until_idle)
        # warm compiles out of the clock: one prompt per distinct
        # pow2 handoff bucket the timed traffic will hit, so the
        # per-bucket inject/extract executables all exist before the
        # timer starts (same warm set for both modes — the engines'
        # lane/prefill compiles stay comparable)
        from paddle_tpu.inference.disagg import _pow2_pad
        seen_buckets = set()
        for p in sorted(prompts, key=len):
            b = _pow2_pad(-(-(len(p) + 1) // page_size))
            if b in seen_buckets:
                continue
            seen_buckets.add(b)
            submit(p, max_new_tokens=2)
        drain()
        w0, t0 = eng.stats["decode_wall_s"], eng.stats["decode_tokens"]
        reqs = [submit(p, max_new_tokens=n_tokens) for p in prompts]
        drain()
        dw = eng.stats["decode_wall_s"] - w0
        dt = eng.stats["decode_tokens"] - t0
        out[f"{mode}_ms_per_token"] = round(1000.0 * dw / max(dt, 1), 3)
        tokens[mode] = [r.result(5) for r in reqs]
        ttfts = [r.ttft_s for r in reqs if r.ttft_s is not None]
        if ttfts:
            out[f"{mode}_ttft_p50_ms"] = round(
                1000.0 * sorted(ttfts)[len(ttfts) // 2], 3)
        if mode == "disagg":
            st = pipe.status()
            out["handoffs"] = int(st["handoffs"])
            out["prefill_workers"] = int(st["stages"]["prefill"]["workers"])
            out["worker_prefills"] = int(st["worker_prefills"])
            out["decode_prefills"] = int(eng.stats["prefills"])
            pipe.close()
    out["identical_tokens"] = tokens["colocated"] == tokens["disagg"]
    if out["colocated_ms_per_token"] and out["disagg_ms_per_token"]:
        out["tpot_ratio"] = round(out["disagg_ms_per_token"]
                                  / out["colocated_ms_per_token"], 3)
    out["note"] = ("same greedy prompts through the co-located engine vs "
                   "the disaggregated prefill/decode pipeline (KV-page "
                   "handoff); identical_tokens is the bit-parity check; "
                   "decode_prefills==0 proves every prefill ran on a "
                   "prefill worker, not the decode engine")
    return out


def bench_gpt2_decode():
    """Autoregressive-decode serving bench: hundreds of concurrent
    simulated streams through the continuous-batching engine
    (inference/serving.py) over the paged KV cache — tokens/s/chip,
    p50/p99 TTFT/TPOT, goodput, and the paged-vs-dense, fused-vs-eager,
    shared-prefix-on/off, tp-decode and disagg A/Bs. The decode
    analogue of the train-step configs."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.gpt import GPT, GPTConfig

    paddle.seed(0)
    if _SCALE == "ci":
        cfg = GPTConfig(vocab_size=8192, max_position_embeddings=512,
                        hidden_size=128, num_layers=2, num_heads=4,
                        dropout=0.0, attn_dropout=0.0)
        max_batch, max_len, page_size = 4, 160, 8
        streams, max_new = 24, 10
        prompt_lo, prompt_hi = 6, 48
        ab_ctxs, ab_tokens = (32, 64, 128), 6
        fve_streams, fve_tokens = 6, 6
        shp_requests, shp_prefix, shp_tokens = 8, 32, 4
        tpd_streams, tpd_tokens = 4, 6
        dis_streams, dis_tokens = 4, 6
    else:
        cfg = GPTConfig.gpt2_small()
        cfg.dropout = cfg.attn_dropout = 0.0
        max_batch, max_len, page_size = 32, 1024, 16
        streams, max_new = 512, 64
        prompt_lo, prompt_hi = 32, 512
        ab_ctxs, ab_tokens = (128, 512, 960), 16
        fve_streams, fve_tokens = 64, 16
        shp_requests, shp_prefix, shp_tokens = 64, 256, 8
        tpd_streams, tpd_tokens = 16, 16
        dis_streams, dis_tokens = 16, 16
    model = GPT(cfg)
    model.eval()
    eng = ServingEngine(model, max_batch=max_batch, max_len=max_len,
                        page_size=page_size, name="gpt2_decode")
    rng = np.random.default_rng(0)
    reqs = []
    t0 = time.perf_counter()
    for _ in range(streams):
        plen = int(rng.integers(prompt_lo, prompt_hi))
        reqs.append(eng.submit(
            rng.integers(1, cfg.vocab_size, (plen,)).tolist(),
            max_new_tokens=max_new))
    eng.run_until_idle(max_iterations=streams * (max_new + 4) + 1000)
    wall = time.perf_counter() - t0
    ttfts = [r.ttft_s for r in reqs if r.ttft_s is not None]
    tpots = [r.tpot_s for r in reqs if r.tpot_s is not None]
    qwaits = [r.admitted_ts - r.submitted_ts for r in reqs
              if r.admitted_ts is not None]
    goodput = sum(len(r.generated) for r in reqs)
    st = eng.status()["stats"]

    def _pct(vals, q):
        return round(float(np.percentile(vals, q)), 4) if vals else None

    ab = {}
    try:
        ab = _paged_vs_dense_ab(model, ab_ctxs, page_size,
                                n_tokens=ab_tokens)
    except Exception as e:
        ab = {"error": f"{type(e).__name__}: {e}"}
    try:
        fve_prompts = [rng.integers(1, cfg.vocab_size,
                                    (int(rng.integers(prompt_lo,
                                                      prompt_hi)),)).tolist()
                       for _ in range(fve_streams)]
        fused_vs_eager = _fused_vs_eager_ab(
            model, fve_prompts, max_batch, max_len, page_size,
            n_tokens=fve_tokens)
    except Exception as e:
        fused_vs_eager = {"error": f"{type(e).__name__}: {e}"}
    try:
        shared_prefix = _shared_prefix_ab(
            model, max_batch, max_len, page_size,
            n_requests=shp_requests, prefix_len=shp_prefix,
            n_tokens=shp_tokens)
    except Exception as e:
        shared_prefix = {"error": f"{type(e).__name__}: {e}"}
    try:
        tpd_prompts = [rng.integers(1, cfg.vocab_size,
                                    (int(rng.integers(prompt_lo,
                                                      prompt_hi)),)).tolist()
                       for _ in range(tpd_streams)]
        tp_decode = _tp_decode_ab(model, tpd_prompts, max_batch, max_len,
                                  page_size, n_tokens=tpd_tokens)
    except Exception as e:
        tp_decode = {"error": f"{type(e).__name__}: {e}"}
    try:
        dis_prompts = [rng.integers(1, cfg.vocab_size,
                                    (int(rng.integers(prompt_lo,
                                                      prompt_hi)),)).tolist()
                       for _ in range(dis_streams)]
        disagg = _disagg_ab(model, dis_prompts, max_batch, max_len,
                            page_size, n_tokens=dis_tokens)
    except Exception as e:
        disagg = {"error": f"{type(e).__name__}: {e}"}
    # serving metric families from the live registry, scoped to this
    # config's observability block (check_bench_result validates them).
    # Snapshotted AFTER the A/B probes so the handoff/per-stage families
    # the disagg pipeline populates land in the same artifact.
    obs = {}
    try:
        from paddle_tpu.profiler import metrics as _metrics
        snap = _metrics.default_registry().snapshot()
        obs["metrics"] = {k: v for k, v in snap.items()
                          if k.startswith(("serving_", "slo_"))}
    except Exception as e:
        obs["metrics_error"] = f"{type(e).__name__}: {e}"
    # request-scoped trace + SLO-window blocks (profiler/reqtrace.py /
    # profiler/slo.py — the /requests and /slo endpoint payloads), so a
    # BENCH round carries per-phase latency attribution
    try:
        obs["reqtrace"] = eng.requests_snapshot(n=min(streams, 50))
    except Exception as e:
        obs["reqtrace"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        obs["slo"] = eng.slo.snapshot()
    except Exception as e:
        obs["slo"] = {"error": f"{type(e).__name__}: {e}"}
    return {
        "name": (f"gpt-decode {cfg.num_layers}L-h{cfg.hidden_size} "
                 f"continuous batching b{max_batch} x {streams} streams "
                 f"(paged KV, page={page_size}, max_len={max_len})"),
        "platform": _platform(),
        "scale": _SCALE,
        "streams": streams,
        "max_new_tokens": max_new,
        "tokens_per_sec_chip": round(goodput / wall, 1),
        "decode_tokens_per_sec": (
            round(st["decode_tokens"] / st["decode_wall_s"], 1)
            if st["decode_wall_s"] else None),
        "goodput_tokens": int(goodput),
        "completed": int(st["completed"]),
        "preemptions": int(st["preemptions"]),
        "batch_occupancy_mean": (
            round(st["decode_tokens"] / max(st["iterations"], 1), 2)),
        "serving": {
            "ttft_s": {"p50": _pct(ttfts, 50), "p99": _pct(ttfts, 99)},
            "tpot_s": {"p50": _pct(tpots, 50), "p99": _pct(tpots, 99)},
            "queue_wait_s": {"p50": _pct(qwaits, 50),
                             "p99": _pct(qwaits, 99)},
            "wall_s": round(wall, 2),
            "prefill_buckets": eng.status()["prefill_buckets"],
            "note": ("TTFT includes queue wait + bucketed prefill (and, "
                     "for early requests, one-time executable compiles); "
                     "TPOT is per finished request, first->last token"),
        },
        "paged_vs_dense": ab,
        "fused_vs_eager": fused_vs_eager,
        "shared_prefix": shared_prefix,
        "tp_decode": tp_decode,
        "disagg": disagg,
        "program_audit": _program_audit_block(lambda: eng.audit()),
        "observability": obs,
    }


def bench_resnet50(B=None, hw=None, depth=50, probe_iters=None):
    """Synthetic-ImageNet ResNet train step (BASELINE.md primary metric).
    The size knobs exist so the harness tests can exercise the full probe/
    compare logic at CPU-feasible shapes; the bench runs the (scale-aware)
    defaults."""
    if B is None:
        B = _scaled(128, 8)
    if hw is None:
        hw = _scaled(224, 64)
    if probe_iters is None:
        probe_iters = _scaled(8, 2)
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.resnet import ResNet, BasicBlock, BottleneckBlock
    from paddle_tpu.nn import functional as F

    rng = np.random.default_rng(0)
    img_np = rng.normal(size=(B, 3, hw, hw)).astype("float32")
    imgs = {"NCHW": paddle.to_tensor(img_np),
            "NHWC": paddle.to_tensor(
                np.ascontiguousarray(img_np.transpose(0, 2, 3, 1)))}
    labels = paddle.to_tensor(rng.integers(0, 1000, (B,)).astype("int32"))

    def build(rc, df, fused, fused_conv=True):
        paddle.seed(0)
        block = BottleneckBlock if depth >= 50 else BasicBlock
        model = ResNet(block, depth, recompute=rc, data_format=df,
                       fused_bn=fused, fused_conv_bn=fused_conv)
        opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                 parameters=model.parameters())
        return TrainStep(model, F.cross_entropy, opt,
                         amp_dtype=jnp.bfloat16)

    # autotune over (remat x data_format) for the FUSED-BN path (reference
    # phi/kernels/autotune/ pattern), plus unfused reference probes at both
    # layouts — the fused-vs-unfused delta is the r6 headline (the Pallas
    # fused BN(+add)+ReLU family, this round's kernel work). Each probe also
    # keeps its executable's cost-analysis bytes so the HBM reduction is
    # measured in the same run it is claimed for.
    probes, probe_errs = {}, {}
    variants = [(rc, df, True) for rc in (False, True)
                for df in ("NCHW", "NHWC")]
    variants += [(False, df, False) for df in ("NCHW", "NHWC")]
    for rc, df, fused in variants:
        try:
            sec_p, _, _, nbytes_p = _run_config(
                build(rc, df, fused), (imgs[df], labels), iters=probe_iters,
                warmup=2)
            probes[(rc, df, fused)] = (sec_p, nbytes_p)
        except Exception as e:  # record, don't swallow: if ALL variants
            probe_errs[(rc, df, fused)] = f"{type(e).__name__}: {e}"
    fused_probes = {k: v for k, v in probes.items() if k[2]}
    if not fused_probes:
        raise RuntimeError(f"all resnet probe variants failed: {probe_errs}")
    best_rc, best_df, _ = min(fused_probes,
                              key=lambda k: fused_probes[k][0])
    from paddle_tpu.ops.pallas import fused_conv_bn as _fcb
    fcb_stats0 = dict(_fcb._stats)
    step = build(best_rc, best_df, fused=True)
    sec, loss, flops, nbytes = _run_config(step, (imgs[best_df], labels),
                                           profile_label="resnet50")
    fcb_engaged = {k: _fcb._stats[k] - fcb_stats0.get(k, 0)
                   for k in _fcb._stats}
    # conv-fusion A/B probe (the r06 headline knob): the main timed run
    # above IS the on side (fused_conv defaults True there — re-building
    # it would only pay a second identical multi-minute XLA compile);
    # the off side runs fused_conv_bn=False at the SAME iters/warmup so
    # the probe-vs-probe ratio carries no amortization bias, with
    # cost-analysis bytes kept so the HBM-bytes/step reduction is
    # measured in-round
    conv_fusion = {"enabled": True,
                   "kernel_stats": fcb_engaged,
                   "engaged": fcb_engaged.get("pallas_fwd", 0) > 0
                   or fcb_engaged.get("xla_fwd", 0) > 0,
                   "micro_ab": _conv_fusion_micro_ab(B=B)}
    try:
        sec_cf_on, nbytes_cf_on = sec, nbytes
        sec_cf_off, _, _, nbytes_cf_off = _run_config(
            build(best_rc, best_df, True, fused_conv=False),
            (imgs[best_df], labels))
        conv_fusion.update({
            "probe_ms_on": round(1000 * sec_cf_on, 2),
            "probe_ms_off": round(1000 * sec_cf_off, 2),
            "speedup_vs_off": round(sec_cf_off / sec_cf_on, 3),
            "hbm_gb_per_step_on": (round(nbytes_cf_on / 1e9, 2)
                                   if nbytes_cf_on else None),
            "hbm_gb_per_step_off": (round(nbytes_cf_off / 1e9, 2)
                                    if nbytes_cf_off else None),
            "hbm_pct_saved": (round(100.0 * (1.0 - nbytes_cf_on
                                             / nbytes_cf_off), 1)
                              if nbytes_cf_on and nbytes_cf_off else None),
            "note": ("fused_conv_bn=True folds the BN statistics pass "
                     "into the 1x1-conv Pallas kernel "
                     "(ops/pallas/fused_conv_bn.py) on eligible shapes; "
                     "probe-vs-probe at the winning layout/remat. On "
                     "platforms where no shape is eligible (CPU) both "
                     "sides compile the same program and the deltas "
                     "read ~0 — `engaged` says whether the kernel ran."),
        })
    except Exception as e:
        conv_fusion["error"] = f"{type(e).__name__}: {e}"
    tuned_vs_static = _tuned_vs_static_probe(
        lambda: build(best_rc, best_df, True), (imgs[best_df], labels),
        iters=probe_iters, warmup=2)
    # unfused comparison at the winning layout/remat (compiled in this same
    # run; probe-length timing is enough for the ratio)
    unfused = probes.get((best_rc, best_df, False))
    if unfused is None:
        try:
            sec_u, _, _, nbytes_u = _run_config(
                build(best_rc, best_df, False), (imgs[best_df], labels),
                iters=probe_iters, warmup=2)
            unfused = (sec_u, nbytes_u)
        except Exception as e:
            probe_errs[(best_rc, best_df, False)] = f"{type(e).__name__}: {e}"
    hbm_unfused = unfused[1] if unfused else None
    # ResNet-50 fwd = 4.09 GFLOP per 224x224 image; train = fwd + ~2x bwd
    model_flops = 3 * 4.09e9 * B * (hw / 224.0) ** 2
    out = {
        "name": (f"resnet{depth} b{B} {hw}x{hw} bf16 {best_df} fused-BN "
                 "(synthetic ImageNet"
                 + (", per-stage remat" if best_rc else "") + ")"),
        "platform": _platform(),
        "scale": _SCALE,
        "conv_fusion": conv_fusion,
        "tuned_vs_static": tuned_vs_static,
        "program_audit": _program_audit_block(
            lambda: [step.audit(imgs[best_df], labels)]),
        "samples_per_sec_chip": round(B / sec, 1),
        "step_time_ms": round(1000 * sec, 2),
        "final_loss": round(loss, 4),
        "mfu": round(model_flops / sec / PEAK_FLOPS, 4),
        "hw_flops_util": round(flops / sec / PEAK_FLOPS, 4) if flops else None,
        "hbm_gb_per_step": round(nbytes / 1e9, 2) if nbytes else None,
        "estimates_note": ESTIMATES_NOTE,
        "probe_ms": {
            f"{'fused' if fu else 'unfused'},remat={rc},{df}":
                round(1000 * t, 1)
            for (rc, df, fu), (t, _) in sorted(probes.items(),
                                               key=lambda kv: kv[1][0])},
        "note": ("fused Pallas BN(+add)+ReLU train kernels "
                 "(ops/pallas/fused_bn.py) replace the unfused BN chain "
                 "whose ~9 full-activation HBM passes pinned model-MFU near "
                 "0.15 (r5 analysis); unfused probes kept for the delta."),
    }
    if probe_errs:
        out["probe_errors"] = {f"remat={rc},{df},fused={fu}": err
                               for (rc, df, fu), err in probe_errs.items()}
    if nbytes and hbm_unfused:
        out["hbm_gb_per_step_unfused"] = round(hbm_unfused / 1e9, 2)
        out["hbm_pct_saved_vs_unfused"] = round(
            100.0 * (1.0 - nbytes / hbm_unfused), 1)
    fused_probe = probes.get((best_rc, best_df, True))
    if unfused and fused_probe:
        # probe-vs-probe at the same config: identical iters/warmup on both
        # sides, so amortization bias doesn't inflate the headline ratio
        out["fused_speedup_vs_unfused"] = round(
            unfused[0] / fused_probe[0], 3)
    return out


def bench_bert_base():
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.bert import Bert, BertConfig
    from paddle_tpu.nn import functional as F
    from paddle_tpu import nn

    # ERNIE/BERT-Base seq128 (BASELINE.md primary metric). b256 saturates
    # the chip (sweep r5: b32 0.25 / b128 0.58 / b256 0.60 / b512 0.28 MFU);
    # dropout=0 matches the GPT flagship convention — with dropout the step
    # is mask-RNG-bound, which the rbg default PRNG already halves.
    B, L = _scaled((256, 128), (8, 64))
    paddle.seed(0)
    cfg = BertConfig.base()
    cfg.max_position_embeddings = max(cfg.max_position_embeddings, L)
    for attr in ("dropout", "hidden_dropout", "attn_dropout",
                 "hidden_dropout_prob", "attention_probs_dropout_prob"):
        if hasattr(cfg, attr):
            setattr(cfg, attr, 0.0)

    class BertCls(nn.Layer):
        def __init__(self):
            super().__init__()
            self.bert = Bert(cfg)
            self.head = nn.Linear(cfg.hidden_size, 2)

        def forward(self, ids):
            _, pooled = self.bert(ids)
            return self.head(pooled)

    model = BertCls()
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = TrainStep(model, F.cross_entropy, opt, amp_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (B, L)).astype("int32"))
    labels = paddle.to_tensor(rng.integers(0, 2, (B,)).astype("int32"))
    sec, loss, flops, nbytes = _run_config(step, (ids, labels),
                                           profile_label="bert_base_seq128")
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    model_flops = (6 * n_params * B * L
                   + 12 * cfg.num_layers * B * L * L * cfg.hidden_size)
    return {
        "name": f"bert-base seq{L} b{B} bf16 dropout0 (ERNIE-Base class)",
        "platform": _platform(),
        "scale": _SCALE,
        "program_audit": _program_audit_block(
            lambda: [step.audit(ids, labels)]),
        "samples_per_sec_chip": round(B / sec, 1),
        "step_time_ms": round(1000 * sec, 2),
        "final_loss": round(loss, 4),
        "mfu": round(model_flops / sec / PEAK_FLOPS, 4),
        "hw_flops_util": round(flops / sec / PEAK_FLOPS, 4) if flops else None,
        "hbm_gb_per_step": round(nbytes / 1e9, 2) if nbytes else None,
        "estimates_note": ESTIMATES_NOTE,
    }


def bench_wide_deep_ps():
    """Wide&Deep over the native parameter server (BASELINE.md row 4).

    Runs in a CPU-forced subprocess: PS-mode trainers are host-CPU
    workers in the reference too (`HogwildWorker`), and the eager PS loop
    on the TPU tunnel would measure per-op dispatch latency, not the
    sparse path."""
    import json as _json
    import os
    import subprocess
    import sys

    # BOTH the env var and the config update, set before any backend can
    # initialize: against the axon plugin only the ENV VAR sticks —
    # jax.config.update alone still binds the TPU (verified live in the r4
    # review, where this child silently measured tunnel latency and
    # reported it as PS throughput). The child re-asserts the platform and
    # emits it in the JSON so a regression here can never be silent again.
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu';"
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import bench, json; print('WDJSON'+json.dumps(bench._wide_deep_ps_body()))")
    proc = subprocess.run([sys.executable, "-c", code],
                          cwd=os.path.dirname(os.path.abspath(__file__)),
                          env=env,
                          capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        # a crash during teardown (e.g. a PS shutdown regression) must not
        # masquerade as a clean run even if the metrics line was flushed
        raise RuntimeError(f"wide&deep bench subprocess rc="
                           f"{proc.returncode}: {proc.stderr[-800:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("WDJSON"):
            return _json.loads(line[len("WDJSON"):])
    raise RuntimeError(f"wide&deep bench subprocess printed no metrics: "
                       f"{proc.stderr[-800:]}")


def _wide_deep_ps_body():
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.ps import PSServer, PSClient
    from paddle_tpu.models.wide_deep import WideDeep

    platform = jax.devices()[0].platform
    assert platform == "cpu", (
        f"PS trainer bench must run on host CPU, got {platform!r}: the "
        "CPU-forcing failed and the number would measure tunnel latency")
    B, SLOTS, VOCAB = 512, 8, 1_000_000
    server = PSServer(0)
    client = PSClient([server.endpoint])
    try:
        paddle.seed(0)
        model = WideDeep(num_slots=SLOTS, embedding_dim=16, dense_dim=13,
                         hidden=64, client=client)
        opt = optimizer.Adam(learning_rate=1e-3,
                             parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        rng = np.random.default_rng(0)

        def batch():
            ids = paddle.to_tensor(
                rng.integers(0, VOCAB, (B, SLOTS)).astype(np.int64))
            dense = paddle.to_tensor(
                rng.normal(size=(B, 13)).astype(np.float32))
            labels = paddle.to_tensor(
                (rng.random((B, 1)) > 0.5).astype(np.float32))
            return ids, dense, labels

        data = [batch() for _ in range(8)]
        for ids, dense, labels in data[:2]:  # warmup
            loss = crit(model(ids, dense), labels)
            loss.backward(); opt.step(); opt.clear_grad()
        t0 = time.perf_counter()
        iters = 20
        for i in range(iters):
            ids, dense, labels = data[i % len(data)]
            loss = crit(model(ids, dense), labels)
            loss.backward(); opt.step(); opt.clear_grad()
        final = float(loss)
        dt = time.perf_counter() - t0
        # PS-relevant metric families from THIS subprocess's registry (the
        # parent's global snapshot can't see them)
        obs = {}
        try:
            from paddle_tpu.profiler import metrics as _metrics
            snap = _metrics.default_registry().snapshot()
            obs["metrics"] = {k: v for k, v in snap.items()
                              if k.startswith(("retry_", "fault_", "ps_",
                                               "heter_", "embed_cache_"))}
        except Exception as e:
            obs["metrics_error"] = f"{type(e).__name__}: {e}"
        return {
            "name": f"wide&deep sparse-PS b{B} x {SLOTS} slots "
                    f"(1M-feasign space, native PS, CPU trainer)",
            "examples_per_sec": round(B * iters / dt, 1),
            "step_time_ms": round(1000 * dt / iters, 2),
            "final_loss": round(final, 4),
            "platform": platform,
            "observability": obs,
        }
    finally:
        client.stop_servers()


def bench_wide_deep_ps_tpu():
    """Wide&Deep with the heterogeneous split: native PS owns the sparse
    tables on host, ONE compiled step runs the dense net fwd+bwd+update on
    the chip (SURVEY §7 "host PS + TPU dense path"; reference heter_ps/).
    Runs in the main (TPU) process — this config is the point: the dense
    path on the accelerator, unlike bench_wide_deep_ps's all-CPU trainer.

    PR-4 shape: mode="pipelined" prefetches the next batch's route/unique/
    pull/H2D on a background stage while the chip executes the current
    step, and the device-side hot-row cache serves repeat feasigns with an
    on-chip gather (gradients absorbed on-chip, written back on eviction/
    flush). A short async-mode probe (the r05 configuration) rides along
    for the speedup ratio, and the per-step stage breakdown lands under
    this config's `observability.heter_breakdown`."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.ps import PSServer, PSClient
    from paddle_tpu.distributed.ps.heter import HeterPSTrainStep
    from paddle_tpu.models.wide_deep import WideDeep

    B, SLOTS, VOCAB = 512, 8, 1_000_000
    CACHE_ROWS = 1 << 15  # holds the whole repeating working set (~32k/table)
    server = PSServer(0)
    client = PSClient([server.endpoint])
    try:
        paddle.seed(0)
        model = WideDeep(num_slots=SLOTS, embedding_dim=16, dense_dim=13,
                         hidden=64, client=client)
        crit = nn.BCEWithLogitsLoss()
        rng = np.random.default_rng(0)

        def batch():
            ids = paddle.to_tensor(
                rng.integers(0, VOCAB, (B, SLOTS)).astype(np.int64))
            dense = paddle.to_tensor(
                rng.normal(size=(B, 13)).astype(np.float32))
            labels = paddle.to_tensor(
                (rng.random((B, 1)) > 0.5).astype(np.float32))
            return ids, dense, labels

        data = [batch() for _ in range(8)]

        # -- async-mode probe (the r05 configuration) for the ratio -------
        probe_iters = 10
        opt_a = optimizer.Adam(learning_rate=1e-3,
                               parameters=model.parameters())
        step_a = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt_a,
                                  mode="async")
        try:
            for ids, dense, labels in data[:2]:
                step_a(ids, dense, labels)
            ta = time.perf_counter()
            for i in range(probe_iters):
                step_a(*data[i % len(data)])
            step_a.flush()
            async_ms = 1000 * (time.perf_counter() - ta) / probe_iters
        finally:
            step_a.close()

        # -- pipelined + hot-row cache (the headline) ---------------------
        opt = optimizer.Adam(learning_rate=1e-3,
                             parameters=model.parameters())
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt,
                                mode="pipelined",
                                cache_capacity=CACHE_ROWS)
        iters = 30
        try:
            # warmup: pass 1 compiles the miss-heavy shapes and fills the
            # cache; pass 2 compiles the steady-state all-hit shapes so the
            # timed window measures the pipeline, not XLA
            for b in data + data:
                step(*b)
            # drain the push worker before touching stage_totals: a still-
            # running warmup push would race the reset (and leak its time
            # into the timed window)
            step.flush()
            for tot in step.stage_totals:
                step.stage_totals[tot] = 0.0 if tot != "steps" else 0
            t0 = time.perf_counter()
            for i in range(iters):
                loss = step(*data[i % len(data)])
                if i + 1 < iters:  # no dead prefetch after the last step
                    step.prefetch(*data[(i + 1) % len(data)])
            step.flush()
            dt = time.perf_counter() - t0
            final = float(loss)
            st = dict(step.stage_totals)
            # compute estimate: a few fully-synced steps (no prefetch is
            # outstanding — the timed loop stopped prefetching before its
            # last step and flush() discards stragglers anyway)
            sync_iters = 5
            ts = time.perf_counter()
            for i in range(iters, iters + sync_iters):
                float(step(*data[i % len(data)]))
            synced_ms = 1000 * (time.perf_counter() - ts) / sync_iters
        finally:
            # join the workers BEFORE stop_servers: an in-flight push
            # racing server shutdown can wedge interpreter exit
            step.close()

        n = max(st["steps"], 1)
        route_ms = 1000 * st["route_s"] / n
        pull_ms = 1000 * st["pull_s"] / n
        put_ms = 1000 * st["put_s"] / n
        push_ms = 1000 * st["push_s"] / n
        wall_ms = 1000 * dt / iters
        sparse_host_ms = route_ms + pull_ms + put_ms
        compute_ms_est = max(0.0, synced_ms - sparse_host_ms)
        hidden_ms = min(sparse_host_ms,
                        max(0.0, sparse_host_ms + compute_ms_est - wall_ms))
        overlap = (hidden_ms / sparse_host_ms) if sparse_host_ms > 0 else 1.0
        caches = list(step.caches.values())
        hits = sum(c.stats["hit"] for c in caches)
        misses = sum(c.stats["miss"] for c in caches)
        breakdown = {
            "route_ms": round(route_ms, 3),
            "pull_ms": round(pull_ms, 3),
            "h2d_ms": round(put_ms, 3),
            "push_ms": round(push_ms, 3),
            "step_wall_ms": round(wall_ms, 3),
            "synced_step_ms": round(synced_ms, 3),
            "compute_ms_est": round(compute_ms_est, 3),
            "sparse_host_ms": round(sparse_host_ms, 3),
            # fraction of host sparse-path time (route+pull+H2D) hidden
            # under on-chip compute; push runs on its own worker thread and
            # is off the critical path by construction
            "pull_overlap_frac": round(overlap, 3),
            "note": ("host-timer derived; compute_ms_est = synced-step "
                     "wall minus host sparse stages (estimate)"),
        }
        cache_stats = {
            "capacity_rows_per_table": CACHE_ROWS,
            "hits": hits, "misses": misses,
            "hit_rate": round(hits / max(hits + misses, 1), 4),
            "evictions": sum(c.stats["eviction"] for c in caches),
            "writebacks": sum(c.stats["writeback"] for c in caches),
        }
        platform = _platform()
        # first measurement of the PR-4 pipelined path against r05's
        # tunnel-serial heter-PS baseline (202.23 ms/step, BENCH_r05 on
        # the TPU bench box) — the ratio is only meaningful on a real
        # TPU tunnel; elsewhere it is recorded null with the baseline
        # kept for the comparison the driver round will make
        r05_ms = 202.23
        return {
            "name": f"wide&deep heter-PS b{B} x {SLOTS} slots "
                    f"(1M-feasign space, native host PS + compiled "
                    f"on-chip dense step, pipelined prefetch + device "
                    f"hot-row cache)",
            "examples_per_sec": round(B * iters / dt, 1),
            "step_time_ms": round(wall_ms, 2),
            "final_loss": round(final, 4),
            "platform": platform,
            "scale": _SCALE,
            "async_probe_step_ms": round(async_ms, 2),
            "pipelined_speedup_vs_async": round(async_ms / wall_ms, 3)
            if wall_ms else None,
            "r05_tunnel_serial_step_ms": r05_ms,
            "speedup_vs_r05_tunnel_serial": (
                round(r05_ms / wall_ms, 3)
                if wall_ms and platform not in ("cpu",) else None),
            "observability": {
                "heter_breakdown": breakdown,
                "embed_cache": cache_stats,
            },
        }
    finally:
        client.stop_servers()


def _init_backend_with_retry(tries: int = 3, probe_timeout: float = 180.0):
    """Initialize the jax backend, retrying with backoff.

    The round-3 bench produced NOTHING because a wedged TPU (a leaked test
    child held the chip) escaped every guard. Two failure shapes matter:
    init RAISING (transient) and init HANGING forever (the observed one) —
    so the probe runs in a daemon thread with a deadline; on hang we give
    up and report, instead of blocking until the driver kills us with no
    JSON emitted. Returns None on success, else the last error string.
    """
    import threading

    err = None
    for i in range(tries):
        box = {}

        def probe():
            try:
                import jax
                jax.devices()
                box["ok"] = True
            except Exception as e:
                box["err"] = f"{type(e).__name__}: {e}"

        th = threading.Thread(target=probe, daemon=True)
        th.start()
        th.join(probe_timeout)
        if box.get("ok"):
            return None
        if th.is_alive():
            # hung C call: unkillable; report and let main() exit hard
            global _INIT_HUNG
            _INIT_HUNG = True
            return (f"backend init hung >{probe_timeout:.0f}s "
                    "(TPU wedged or tunnel dead)")
        err = box.get("err", "unknown init failure")
        # jax caches a failed init; clear cached backends before retry
        try:
            from jax._src import xla_bridge as _xb
            _xb._clear_backends()
        except Exception:
            pass
        if i < tries - 1:
            time.sleep(10 * (i + 1))
    return err


def main(argv=None):
    """argv defaults to NO arguments — programmatic callers (the harness
    tests) run the default bench; the CLI passes sys.argv[1:] itself."""
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("--profile-steps", type=int, default=None, metavar="N",
                    help="after each config's timed run, capture N extra "
                         "steps in a jax.profiler session and report "
                         "measured (xplane-correlated) device time next "
                         "to the cost-model estimates (DEFAULT ON: "
                         f"{DEFAULT_PROFILE_STEPS} steps; 0 disables)")
    ap.add_argument("--no-profile-steps", action="store_true",
                    help="opt out of the default-on measured-attribution "
                         "capture (equivalent to --profile-steps 0)")
    ap.add_argument("--scale", choices=("full", "ci"), default=None,
                    help="'full' = the TPU bench-box config every round "
                         "before r06 ran (default); 'ci' = CPU-feasible "
                         "dims/iters, same models and probe blocks, "
                         "recorded as scale=ci per config (env "
                         "PADDLE_TPU_BENCH_SCALE)")
    args = ap.parse_args(argv or [])
    global _PROFILE_STEPS, _SCALE
    if args.scale is not None:
        _SCALE = args.scale
    if args.no_profile_steps:
        _PROFILE_STEPS = 0
    elif args.profile_steps is None:
        _PROFILE_STEPS = max(0, DEFAULT_PROFILE_STEPS)
    else:
        _PROFILE_STEPS = max(0, int(args.profile_steps))
    result = {
        "metric": "gpt2-small-124M train tokens/sec/chip "
                  "(b8 x s1024, bf16 compute + fp32 master, fused step)",
        "value": None,
        "unit": "tokens/sec/chip",
        "vs_baseline": None,
        "platform": None,  # filled after backend init
        "configs": {},
        "note": "reference publishes no in-repo baseline "
                "(BASELINE.json published:{}); peak for MFU = "
                f"{PEAK_FLOPS/1e12:.0f} TFLOP/s bf16; " + ESTIMATES_NOTE,
    }
    configs = result["configs"]
    try:
        from paddle_tpu.profiler import server as _obs_server
        _obs_server.maybe_start_server()  # PADDLE_TPU_METRICS_PORT opt-in
    except Exception:
        pass
    init_err = _init_backend_with_retry()
    if init_err is not None:
        result["error"] = f"jax backend init failed after retries: {init_err}"
        print(json.dumps(result))
        if _INIT_HUNG:
            # a hung init probe leaves an unkillable daemon thread holding
            # the backend lock — exit hard so the JSON (already flushed) is
            # the process's last word instead of a shutdown deadlock
            import sys
            sys.stdout.flush()
            os._exit(0)
        return
    result["platform"] = _platform()
    try:
        from paddle_tpu.ops.pallas import autotune as _at
    except Exception:
        _at = None
    # EVERY config — including the flagship — inside the guard: one failure
    # must not sink the whole bench (the round-3 lesson).
    for fn, key in ((bench_gpt2, "gpt2_small"),
                    (bench_gpt2_decode, "gpt2_decode"),
                    (bench_resnet50, "resnet50"),
                    (bench_bert_base, "bert_base_seq128"),
                    (bench_wide_deep_ps, "wide_deep_ps"),
                    (bench_wide_deep_ps_tpu, "wide_deep_ps_tpu")):
        ev0 = _at.events_snapshot() if _at is not None else {}
        n_tuned0 = len(_at.tuned_log()) if _at is not None else 0
        try:
            configs[key] = fn()
        except Exception as e:
            import traceback
            configs[key] = {"error": f"{type(e).__name__}: {e}",
                            "traceback": traceback.format_exc(limit=6)}
        # kernel-autotune activity attributed to THIS config's run (event
        # deltas + the tune/disk-hit log slice), validated by
        # tools/check_bench_result.py
        if _at is not None and isinstance(configs.get(key), dict):
            try:
                ev1 = _at.events_snapshot()
                configs[key]["autotune"] = {
                    "enabled": _at.enabled(),
                    "mode": _at.mode(),
                    "cache_dir": _at.cache_dir() or None,
                    "events": {k: ev1[k] - ev0.get(k, 0.0) for k in ev1
                               if ev1[k] - ev0.get(k, 0.0) > 0},
                    "tuned": _at.tuned_log()[n_tuned0:],
                }
            except Exception:
                pass
    # measured-device-time capture results per config (--profile-steps)
    for key, prof in _PROFILE_RESULTS.items():
        if key in configs and isinstance(configs[key], dict):
            configs[key]["profile"] = prof
    gpt = configs.get("gpt2_small", {})
    if "tokens_per_sec_chip" in gpt:
        result["value"] = gpt["tokens_per_sec_chip"]
        result["step_time_ms"] = gpt["step_time_ms"]
        result["mfu"] = gpt["mfu"]
    else:
        result["error"] = ("flagship gpt2 config failed: "
                           + str(gpt.get("error", "missing")))
    result["observability"] = _observability_snapshot()
    print(json.dumps(result))


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
