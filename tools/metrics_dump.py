#!/usr/bin/env python
"""Pretty-print a paddle_tpu metrics snapshot for humans.

Input forms (auto-detected):
  * a raw `MetricsRegistry.snapshot()` JSON file;
  * a bench output / driver `BENCH_r{N}.json` whose `observability.metrics`
    holds the snapshot (the shape bench.py emits since PR 2);
  * a LIVE endpoint of a running job's ObservabilityServer — either its
    `/snapshot` JSON or its `/metrics` Prometheus text (parsed back into
    the snapshot shape), via `--url` or an http(s):// positional;
  * `-` for stdin.

CLI:
    python tools/metrics_dump.py BENCH_r06.json
    python tools/metrics_dump.py snapshot.json --filter collective
    python tools/metrics_dump.py --url http://host:9400/metrics
    python tools/metrics_dump.py --url http://host:9400/snapshot --filter heter
    python tools/metrics_dump.py BENCH_r16.json --serving
    python tools/metrics_dump.py BENCH_r17.json --requests
    python tools/metrics_dump.py --url http://host:9400/requests --requests
    python bench.py | python tools/metrics_dump.py -

Exit code 0 on success, 2 on unusable input.
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Optional


def _extract_snapshot(doc) -> Optional[dict]:
    """Find a metrics snapshot in any of the accepted document shapes."""
    if not isinstance(doc, dict):
        return None
    # registry snapshot: every value is {kind, ...}
    if doc and all(isinstance(v, dict) and "kind" in v for v in doc.values()):
        return doc
    obs = doc.get("observability")
    if isinstance(obs, dict) and isinstance(obs.get("metrics"), dict):
        return obs["metrics"]
    if isinstance(doc.get("metrics"), dict):
        return _extract_snapshot(doc["metrics"]) or doc["metrics"]
    # driver BENCH_r{N}.json wrapper: the bench object sits under
    # `parsed` (or as the raw output line in `tail`)
    if isinstance(doc.get("parsed"), dict):
        snap = _extract_snapshot(doc["parsed"])
        if snap is not None:
            return snap
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return _extract_snapshot(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return None


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        v = int(v)
    if isinstance(v, int):
        return f"{v:,}"
    return f"{v:.6g}"


def _fmt_labels(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items())) or "-"


_PROM_LINE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$")
_PROM_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _prom_unescape(v: str) -> str:
    # left-to-right scan: sequential str.replace decodes the tail of an
    # escaped backslash ("\\n" -> backslash+newline instead of "\n")
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt in ("\\", '"'):
                out.append(nxt)
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def parse_prometheus_text(txt: str, prefix: str = "paddle_tpu_") -> dict:
    """Parse a /metrics exposition back into the registry-snapshot shape
    ({name: {kind, help, values}}), reassembling histograms from their
    _bucket/_sum/_count series — so the same pretty-printer serves files
    AND a live endpoint."""
    kinds, helps = {}, {}
    # series accumulation: plain -> [(labels, value)], hist -> per-labelkey
    plain: dict = {}
    hist: dict = {}

    def strip(name: str) -> str:
        return name[len(prefix):] if name.startswith(prefix) else name

    for line in txt.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            kinds[strip(name)] = kind.strip()
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_txt = rest.partition(" ")
            helps[strip(name)] = help_txt
            continue
        if line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if not m:
            continue
        name, _, label_txt, raw = m.groups()
        name = strip(name)
        try:
            value = float(raw)
        except ValueError:
            continue
        labels = {k: _prom_unescape(v)
                  for k, v in _PROM_LABEL.findall(label_txt or "")}
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and kinds.get(name[:-len(suffix)]) \
                    == "histogram":
                base = name[:-len(suffix)]
                part = suffix[1:]
                break
        if base is None:
            plain.setdefault(name, []).append((labels, value))
            continue
        le = labels.pop("le", None)
        key = tuple(sorted(labels.items()))
        series = hist.setdefault(base, {}).setdefault(
            key, {"labels": labels, "buckets": {}, "sum": 0.0, "count": 0})
        if part == "bucket" and le is not None:
            series["buckets"][le] = int(value)
        elif part == "sum":
            series["sum"] = value
        elif part == "count":
            series["count"] = int(value)
    snap = {}
    for name, kind in kinds.items():
        if kind == "histogram":
            snap[name] = {"kind": kind, "help": helps.get(name, ""),
                          "values": list(hist.get(name, {}).values())}
        else:
            snap[name] = {"kind": kind, "help": helps.get(name, ""),
                          "values": [{"labels": l, "value": v}
                                     for l, v in plain.get(name, [])]}
    return snap


def fetch_url(url: str, timeout: float = 10.0) -> Optional[dict]:
    """GET a live ObservabilityServer endpoint and return a snapshot dict
    (handles both /metrics text and /snapshot|bench-shaped JSON)."""
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as r:
        ctype = r.headers.get("Content-Type", "")
        body = r.read().decode()
    if "text/plain" in ctype or body.lstrip().startswith("# "):
        return parse_prometheus_text(body)
    doc = json.loads(body)
    return _extract_snapshot(doc)


def hist_quantile(buckets: dict, q: float) -> Optional[float]:
    """Estimate a quantile from a histogram family's CUMULATIVE bucket
    counts ({upper_bound_repr: cum_count, ..., "+Inf": total}), linearly
    interpolating inside the bucket that crosses the target rank. Returns
    None for an empty histogram; the +Inf bucket clamps to the largest
    finite bound (an under-estimate, like every prometheus quantile)."""
    total = buckets.get("+Inf", 0)
    if not total:
        return None
    target = q * total
    bounds = sorted((float(k), v) for k, v in buckets.items() if k != "+Inf")
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in bounds:
        if cum >= target:
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span else 1.0
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_cum = bound, cum
    return bounds[-1][0] if bounds else None


def format_snapshot(snap: dict, name_filter: str = "") -> str:
    """Render {name: {kind, help, values}} as aligned human-readable rows."""
    lines = []
    for name in sorted(snap):
        if name_filter and name_filter not in name:
            continue
        fam = snap[name]
        kind, values = fam.get("kind", "?"), fam.get("values", [])
        lines.append(f"{name} [{kind}] — {fam.get('help', '')}")
        if not values:
            lines.append("    (no series)")
            continue
        for v in sorted(values, key=lambda d: _fmt_labels(d.get("labels", {}))):
            labels = _fmt_labels(v.get("labels", {}))
            if kind == "histogram":
                cnt, tot = v.get("count", 0), v.get("sum", 0.0)
                avg = tot / cnt if cnt else 0.0
                line = (f"    {labels:<40} count={cnt:,} "
                        f"sum={tot:.6g}s avg={avg:.6g}s")
                buckets = v.get("buckets") or {}
                if cnt and buckets:
                    qs = [(q, hist_quantile(buckets, q))
                          for q in (0.5, 0.95, 0.99)]
                    line += "".join(
                        f" p{int(q * 100)}={est:.4g}s"
                        for q, est in qs if est is not None)
                lines.append(line)
            else:
                lines.append(f"    {labels:<40} {_fmt_value(v.get('value', 0))}")
    return "\n".join(lines) if lines else "(empty snapshot)"


def format_serving(snap: dict) -> str:
    """Serving-focused summary: queue/occupancy/goodput gauges plus the
    TTFT/TPOT latency histograms broken out per decode path (fused vs
    eager) with p50/p95/p99 — the at-a-glance SLO view of a serving
    deployment. Families absent from the snapshot are skipped."""
    lines = ["serving summary"]
    for name in ("serving_queue_depth", "serving_batch_occupancy",
                 "serving_goodput_tokens_total"):
        fam = snap.get(name)
        if not fam:
            continue
        for v in sorted(fam.get("values", []),
                        key=lambda d: _fmt_labels(d.get("labels", {}))):
            labels = _fmt_labels(v.get("labels", {}))
            lines.append(f"    {name:<32} {labels:<24} "
                         f"{_fmt_value(v.get('value', 0))}")
    for name, title in (("serving_ttft_seconds", "ttft"),
                        ("serving_tpot_seconds", "tpot")):
        fam = snap.get(name)
        if not fam:
            continue
        for v in sorted(fam.get("values", []),
                        key=lambda d: _fmt_labels(d.get("labels", {}))):
            labels = v.get("labels", {})
            path = labels.get("path", "?")
            model = labels.get("model", "?")
            cnt = v.get("count", 0)
            buckets = v.get("buckets") or {}
            line = (f"    {title} model={model} path={path:<6} "
                    f"count={cnt:,}")
            if cnt:
                avg = v.get("sum", 0.0) / cnt
                line += f" avg={avg:.6g}s"
                if buckets:
                    line += "".join(
                        f" p{int(q * 100)}={est:.4g}s"
                        for q, est in ((q, hist_quantile(buckets, q))
                                       for q in (0.5, 0.95, 0.99))
                        if est is not None)
            lines.append(line)
    if len(lines) == 1:
        return "serving summary: no serving_* families in snapshot"
    return "\n".join(lines)


def _extract_requests(doc) -> Optional[dict]:
    """Find a request-trace payload (the /requests endpoint shape, also
    emitted as bench observability.reqtrace) in any accepted document."""
    if not isinstance(doc, dict):
        return None
    if "completed" in doc and "live" in doc:
        return doc
    obs = doc.get("observability")
    if isinstance(obs, dict) and isinstance(obs.get("reqtrace"), dict):
        return obs["reqtrace"]
    if isinstance(doc.get("parsed"), dict):
        rt = _extract_requests(doc["parsed"])
        if rt is not None:
            return rt
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return _extract_requests(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return None


def _fmt_phase_ms(phases: dict) -> str:
    parts = [f"{k}={1000 * v:.1f}ms"
             for k, v in sorted(phases.items(), key=lambda kv: -kv[1])
             if isinstance(v, (int, float)) and v > 0]
    return " | ".join(parts) or "no phases"


def format_requests(rt: dict) -> str:
    """Per-request phase breakdown: live then recently-completed traces
    (one line each: trace id, state, preemptions, e2e, phase costs),
    plus the engine's latest introspection snapshot."""
    lines = [f"request traces (model {rt.get('model', '?')}, "
             f"tracer {'on' if rt.get('enabled', True) else 'OFF'})"]
    for t in rt.get("live") or []:
        phases = t.get("phases") or {}
        lines.append(f"    LIVE trace {t.get('trace_id', '?'):>4} "
                     f"request {t.get('rid', '?'):>4} "
                     f"state={t.get('state', '?'):<8} "
                     f"preempt={t.get('preemptions', 0)} "
                     f"tokens={t.get('decode_tokens', 0)}  "
                     f"[{_fmt_phase_ms(phases)}]")
    for t in rt.get("completed") or []:
        e2e = t.get("e2e_s")
        e2e_s = f"{1000 * e2e:.1f}ms" if isinstance(e2e, (int, float)) \
            else "?"
        lines.append(f"    DONE trace {t.get('trace_id', '?'):>4} "
                     f"request {t.get('rid', '?'):>4} "
                     f"{t.get('finish_reason', '?'):<8} "
                     f"preempt={t.get('preemptions', 0)} "
                     f"tokens={t.get('decode_tokens', 0)} "
                     f"e2e={e2e_s}  [{_fmt_phase_ms(t.get('phases') or {})}]")
    intro = rt.get("introspection") or []
    if intro:
        last = intro[-1]
        lines.append(f"    engine @ iteration {last.get('iteration', '?')}: "
                     f"active={last.get('active', '?')} "
                     f"lanes={last.get('lanes', '?')} "
                     f"queue={last.get('queue_depth', '?')} "
                     f"pages free/used/shared="
                     f"{last.get('free_pages', '?')}/"
                     f"{last.get('used_pages', '?')}/"
                     f"{last.get('cow_shared_pages', '?')} "
                     f"({len(intro)} snapshot(s))")
    if len(lines) == 1:
        lines.append("    (no traces recorded)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=None,
                    help="snapshot/bench JSON file, an http(s):// endpoint, "
                         "or - for stdin")
    ap.add_argument("--url", default=None,
                    help="live endpoint of a running job's Observability"
                         "Server (/metrics Prometheus text or /snapshot "
                         "JSON)")
    ap.add_argument("--filter", default="",
                    help="only show metric families whose name contains this")
    ap.add_argument("--json", action="store_true",
                    help="re-emit the extracted snapshot as JSON instead of "
                         "the human table")
    ap.add_argument("--serving", action="store_true",
                    help="serving SLO summary: queue/occupancy/goodput plus "
                         "TTFT/TPOT quantiles per decode path (fused|eager)")
    ap.add_argument("--requests", action="store_true",
                    help="per-request trace view: live + recently-completed "
                         "request phase breakdowns (a /requests endpoint "
                         "payload or bench observability.reqtrace block)")
    args = ap.parse_args(argv)
    url = args.url
    if url is None and args.path and args.path.startswith(("http://",
                                                           "https://")):
        url = args.path
    if url is not None:
        try:
            if args.requests:
                # the /requests payload is not a metrics snapshot: fetch
                # the raw JSON (accepts /requests itself or bench JSON)
                import urllib.request
                with urllib.request.urlopen(url, timeout=10.0) as r:
                    doc = json.loads(r.read().decode())
                rt = _extract_requests(doc)
                if rt is None:
                    print(f"metrics_dump: no request traces in the {url} "
                          f"response (expected the /requests endpoint or "
                          f"bench JSON with observability.reqtrace)",
                          file=sys.stderr)
                    return 2
                print(json.dumps(rt, indent=2, sort_keys=True)
                      if args.json else format_requests(rt))
                return 0
            snap = fetch_url(url)
        except Exception as e:
            print(f"metrics_dump: cannot fetch {url}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        if snap is None:
            print(f"metrics_dump: no metrics snapshot in the {url} "
                  f"response", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(snap, indent=2, sort_keys=True))
        elif args.serving:
            print(format_serving(snap))
        else:
            print(format_snapshot(snap, args.filter))
        return 0
    if args.path is None:
        ap.error("need a file path, -, or --url")
    try:
        txt = sys.stdin.read() if args.path == "-" else open(args.path).read()
    except OSError as e:
        print(f"metrics_dump: {e}", file=sys.stderr)
        return 2
    doc = None
    for line in [txt] + list(reversed(txt.strip().splitlines())):
        try:
            doc = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    if args.requests:
        rt = _extract_requests(doc) if doc is not None else None
        if rt is None:
            print("metrics_dump: no request traces found in input "
                  "(expected a /requests payload or bench JSON with "
                  "observability.reqtrace)", file=sys.stderr)
            return 2
        print(json.dumps(rt, indent=2, sort_keys=True)
              if args.json else format_requests(rt))
        return 0
    snap = _extract_snapshot(doc) if doc is not None else None
    if snap is None:
        print("metrics_dump: no metrics snapshot found in input "
              "(expected a registry snapshot or bench JSON with "
              "observability.metrics)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(snap, indent=2, sort_keys=True))
    elif args.serving:
        print(format_serving(snap))
    else:
        print(format_snapshot(snap, args.filter))
    return 0


if __name__ == "__main__":
    sys.exit(main())
