#!/usr/bin/env python
"""Inspect paddle_tpu distributed checkpoints: header, checksum, spec table.

usage: python tools/ckpt_inspect.py CKPT [CKPT...]
       python tools/ckpt_inspect.py --dir CKPT_DIR   # per-step audit

Per file: magic/format version, payload size, stored vs computed CRC32 and
the verification verdict (OK / CORRUPT with reason / LEGACY for pre-header
plain-pickle files), then — when the payload is loadable — a table of the
saved arrays (tree path, shape, dtype) with their recorded PartitionSpecs,
plus the non-array scalars (epoch/step cursors etc.).

A path that is a SHARDED step directory (the chunked PTSHARD01 layout:
per-rank manifests + one file per array shard) gets the sharded report
instead: the manifest table (rank, world size, generation, mesh axes),
the per-array sharding-spec table, a per-chunk CRC32 verdict, and the
overall step verdict — `complete`, `partial` (shards missing but every
array still reassembles: restore works), `torn` (only prepared-but-
uncommitted manifests), or `corrupt`.

`--dir` renders the per-step COMMIT status across the directory first —
committed / partial / torn-tmp (a `.tmp.prep` prepared by the two-phase
coordinated save but never renamed: barrier abort, or a host that died
between prepare and commit) / corrupt — with the newest-valid verdict
resume would pick, so a barrier abort can be audited without reading
pickles. Sharded step directories and monolithic step files can coexist
in one audit.
"""
from __future__ import annotations

import argparse
import os
import struct
import sys
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _walk(obj, prefix, rows, scalars):
    import numpy as np
    if isinstance(obj, np.ndarray):
        rows.append((prefix or "<root>", tuple(obj.shape), str(obj.dtype),
                     obj.nbytes))
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            _walk(v, f"{prefix}/{k}", rows, scalars)
        return
    if isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _walk(v, f"{prefix}/{i}", rows, scalars)
        return
    scalars.append((prefix or "<root>", repr(obj)))


def inspect_file(path: str) -> dict:
    """Header/CRC/spec report for one checkpoint file (importable for
    tests). Keys: path, status ('ok'|'corrupt'|'legacy'), reason, version,
    payload_bytes, crc_stored, crc_computed, arrays, scalars, specs."""
    from paddle_tpu.distributed import checkpoint as ck

    info = {"path": path, "status": "ok", "reason": None, "version": None,
            "payload_bytes": None, "crc_stored": None, "crc_computed": None,
            "arrays": [], "scalars": [], "specs": {}}
    with open(path, "rb") as f:
        data = f.read()
    hdr = struct.Struct("<8sIQ")
    if data.startswith(b"PTCKPT01"):
        if len(data) >= hdr.size:
            _, crc, length = hdr.unpack_from(data)
            payload = data[hdr.size:]
            info["crc_stored"] = crc
            info["crc_computed"] = zlib.crc32(payload) & 0xFFFFFFFF
            info["payload_bytes"] = len(payload)
    else:
        info["status"] = "legacy"
        info["payload_bytes"] = len(data)
    ok, reason = ck.verify(path)
    if not ok:
        info["status"] = "corrupt"
        info["reason"] = reason
        return info
    import pickle
    payload = data if info["status"] == "legacy" else data[hdr.size:]
    blob = pickle.loads(payload)
    info["version"] = blob.get("version")
    info["specs"] = blob.get("specs", {})
    _walk(blob.get("state"), "", info["arrays"], info["scalars"])
    return info


def _fmt_bytes(n):
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024


def print_report(info: dict):
    print(f"== {info['path']}")
    if info["status"] == "legacy":
        print("   format: LEGACY (pre-header plain pickle, no checksum)")
    else:
        crc_s, crc_c = info["crc_stored"], info["crc_computed"]
        match = "match" if crc_s == crc_c else "MISMATCH"
        print(f"   format: PTCKPT01 v{info['version']}  "
              f"payload {_fmt_bytes(info['payload_bytes'] or 0)}")
        if crc_s is not None:
            print(f"   crc32: stored {crc_s:#010x} / computed {crc_c:#010x} "
                  f"({match})")
    if info["status"] == "corrupt":
        print(f"   status: CORRUPT — {info['reason']}")
        return
    print("   status: OK")
    if info["arrays"]:
        w = max(len(p) for p, *_ in info["arrays"])
        print(f"   {'tree path':{w}s}  shape            dtype     spec")
        total = 0
        for p, shape, dtype, nbytes in info["arrays"]:
            total += nbytes
            spec = info["specs"].get(p, "")
            print(f"   {p:{w}s}  {str(shape):15s}  {dtype:8s}  "
                  f"{spec if spec else '-'}")
        print(f"   {len(info['arrays'])} arrays, {_fmt_bytes(total)} total")
    for p, v in info["scalars"]:
        print(f"   {p} = {v}")


def is_sharded_step(path: str) -> bool:
    """True when `path` is a chunked-format step DIRECTORY (delegates to
    the layout's own predicate so inspector and auto-detector agree)."""
    from paddle_tpu.distributed.sharded_checkpoint import is_step_dir
    return is_step_dir(path)


def inspect_sharded_step(path: str) -> dict:
    """Report for one sharded (chunked) step directory — importable.

    Keys: path, status ('complete'|'partial'|'torn'|'corrupt'|'empty'),
    detail, world_size, manifests [{rank, world_size, generation,
    mesh_axes, n_chunks}], tmp_manifests, arrays [(path, shape, dtype,
    spec)], chunks [{file, path, bytes, verdict}]."""
    from paddle_tpu.distributed import sharded_checkpoint as sc

    # one deep pass: _verify_step_detail hands back its per-chunk
    # verdicts, so a multi-GB step is read+CRC'd once, not twice
    status, detail, scan, verdicts = sc._verify_step_detail(path, deep=True)
    info = {"path": path, "status": status, "detail": detail,
            "world_size": scan.world_size,
            "tmp_manifests": [os.path.basename(p)
                              for p in scan.tmp_manifests],
            "manifests": [], "arrays": [], "chunks": []}
    for rank in sorted(scan.manifests):
        m = scan.manifests[rank]
        info["manifests"].append({
            "rank": rank, "world_size": m["world_size"],
            "generation": m.get("generation"),
            "mesh_axes": m.get("mesh_axes"),
            "n_chunks": len(m["chunks"])})
        for rec in m["chunks"]:
            info["chunks"].append({"file": rec["file"], "path": rec["path"],
                                   "bytes": rec["bytes"],
                                   "verdict": verdicts.get(rec["file"],
                                                           "unverified")})
    if scan.manifests:
        arrays = next(iter(scan.manifests.values()))["arrays"]
        for p in sorted(arrays):
            a = arrays[p]
            info["arrays"].append((p, tuple(a["shape"]), a["dtype"],
                                   a.get("spec")))
    return info


def print_sharded_report(info: dict):
    print(f"== {info['path']} (sharded/chunked step)")
    verdict = info["status"].upper()
    print(f"   status: {verdict} — {info['detail']}")
    if info["status"] == "partial":
        print("   (restore is still possible: surviving chunks cover "
              "every array)")
    for m in info["manifests"]:
        mesh = m["mesh_axes"] or "-"
        print(f"   manifest rank {m['rank']}/{m['world_size']}  "
              f"gen {m['generation']}  mesh {mesh}  "
              f"{m['n_chunks']} chunk(s)")
    for t in info["tmp_manifests"]:
        print(f"   PREPARED-UNCOMMITTED {t} (barrier abort, or host died "
              f"between prepare and commit)")
    if info["arrays"]:
        w = max(len(p) for p, *_ in info["arrays"])
        print(f"   {'tree path':{w}s}  shape            dtype     spec")
        for p, shape, dtype, spec in info["arrays"]:
            print(f"   {p:{w}s}  {str(shape):15s}  {dtype:8s}  "
                  f"{spec if spec else '-'}")
    for c in info["chunks"]:
        mark = "ok" if c["verdict"] == "ok" else f"CORRUPT — {c['verdict']}"
        print(f"   chunk {c['file']:32s} {c['path']:20s} "
              f"{_fmt_bytes(c['bytes']):>8s}  crc {mark}")


def dir_status(dirname: str, prefix: str = "ckpt") -> dict:
    """Per-step commit audit of a checkpoint directory (importable).

    Returns {"steps": [{"step", "status", "reason", "final", "tmps"}, ...]
    newest first, "newest_valid": step or None}. Status per step:
    'committed' (final file verifies), 'corrupt' (final file fails
    header/CRC), 'torn-tmp' (only a `.tmp.prep` barrier tmp exists — the
    two-phase coordinated save aborted, or the host died between prepare
    and commit), 'stale-tmp' (only a plain-write `.tmp.*` exists — a
    single-host atomic save was interrupted; no barrier involved)."""
    from paddle_tpu.distributed.checkpoint import _step_files, verify
    from paddle_tpu.distributed.sharded_checkpoint import _step_dirs

    finals = dict((s, p) for s, p in _step_files(dirname, prefix))
    finals.update((s, p) for s, p in _step_dirs(dirname, prefix))
    tmps: dict = {}
    if os.path.isdir(dirname):
        for fn in os.listdir(dirname):
            if not fn.startswith(prefix + "_") or ".tmp." not in fn:
                continue
            try:
                step = int(fn[len(prefix) + 1:].split(".", 1)[0])
            except ValueError:
                continue
            tmps.setdefault(step, []).append(os.path.join(dirname, fn))
    steps = []
    newest_valid = None
    for step in sorted(set(finals) | set(tmps), reverse=True):
        final = finals.get(step)
        entry = {"step": step, "final": final,
                 "tmps": sorted(tmps.get(step, [])), "reason": None}
        if final is not None and os.path.isdir(final):
            # chunked-layout step directory: verdict from its manifests
            from paddle_tpu.distributed import sharded_checkpoint as sc
            status, detail = sc.verify_step(final, deep=True)
            entry["status"] = {"complete": "committed",
                               "torn": "torn-tmp"}.get(status, status)
            entry["reason"] = detail
            if status in ("complete", "partial") and newest_valid is None:
                newest_valid = step
        elif final is not None:
            ok, reason = verify(final)
            entry["status"] = "committed" if ok else "corrupt"
            entry["reason"] = reason
            if ok and newest_valid is None:
                newest_valid = step
        else:
            # only the barrier's .tmp.prep means "prepared but never
            # committed" — an interrupted PLAIN atomic write also leaves
            # ckpt_<step>.tmp.<suffix> and must not read as a barrier abort
            entry["status"] = ("torn-tmp" if any(
                p.endswith(".tmp.prep") for p in entry["tmps"])
                else "stale-tmp")
        steps.append(entry)
    return {"steps": steps, "newest_valid": newest_valid}


def print_dir_report(dirname: str, st: dict):
    print(f"== {dirname} (per-step commit status)")
    if not st["steps"]:
        print("   no checkpoint files")
        return
    for e in st["steps"]:
        line = f"   step {e['step']:>8d}  {e['status']:9s}"
        if e["status"] == "corrupt":
            line += f"  {e['reason']}"
        elif e["status"] == "partial":
            line += (f"  shards missing but restore possible "
                     f"({e['reason']})")
        elif e["status"] == "torn-tmp":
            line += ("  prepared but never committed (barrier abort, or "
                     "host died between prepare and commit)")
        elif e["status"] == "stale-tmp":
            line += ("  interrupted plain write (no barrier involved); "
                     "safe to GC")
        if e["tmps"] and e["status"] not in ("torn-tmp", "stale-tmp"):
            line += f"  [+{len(e['tmps'])} stale tmp]"
        print(line)
    nv = st["newest_valid"]
    if nv is None:
        print("   newest-valid: NONE — resume would start fresh")
    else:
        print(f"   newest-valid: step {nv} — single-host resume picks it; "
              f"a coordinated fleet resumes from the minimum of every "
              f"host's newest-valid")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", help="checkpoint files")
    ap.add_argument("--dir", help="audit a checkpoint directory: per-step "
                                  "commit status + every ckpt_* file")
    args = ap.parse_args(argv)
    paths = list(args.paths)
    if args.dir:
        st = dir_status(args.dir)
        print_dir_report(args.dir, st)
        paths += [e["final"] for e in st["steps"] if e["final"]]
    if not paths:
        if args.dir:
            return 0
        ap.error("no checkpoint files given")
    bad = 0
    for p in paths:
        if is_sharded_step(p):
            info = inspect_sharded_step(p)
            print_sharded_report(info)
            bad += info["status"] in ("corrupt", "torn")
        else:
            info = inspect_file(p)
            print_report(info)
            bad += info["status"] == "corrupt"
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
