#!/usr/bin/env python
"""Offline static program audit of the shipped models + serving path.

Runs the `paddle_tpu.analysis` program auditor (donation, dtype
hygiene, sharding, executable bloat — trace + lower only, nothing
executes) over the headline configurations and prints the findings:

    python tools/program_audit.py                       # all models, text
    python tools/program_audit.py --model gpt2          # one model
    python tools/program_audit.py --fail-on=high        # CI gate: exit 1
                                                        # on >= high
    python tools/program_audit.py --json                # machine-readable
    python tools/program_audit.py --lint                # convention lints
    python tools/program_audit.py --scale tiny          # smoke shapes

Models: gpt2 (GPT-2-small bf16+fp32-master TrainStep), resnet50
(Momentum TrainStep, fused conv+BN tails), bert (BERT-Base cls head,
bf16 TrainStep), gpt2_decode (the continuous-batching serving engine's
decode + prefill executables). `--scale ci` (default) audits the real
architectures at CPU-feasible batch shapes — the audit is about program
STRUCTURE, which batch size does not change; `--scale tiny` shrinks
depth/width too (fast smoke for the test suite's plumbing checks).

Exit codes: 0 = no findings at/above --fail-on (default: no gate, always
0 unless --fail-on given); 1 = gated findings present (or lint
violations under --lint); 2 = a model failed to build/audit.

This is the CI gate `tests/test_program_audit_gate.py` drives: the
shipped programs must stay high-clean while the seeded-hazard fixtures
in tests/test_analysis.py prove every check fires.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _audit_train_step(step, batch):
    return [step.audit(*batch, emit=False)]


def build_gpt2(scale: str):
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.nn import functional as F

    paddle.seed(0)
    cfg = GPTConfig.gpt2_small()
    if scale == "tiny":
        cfg.num_layers, cfg.hidden_size, cfg.num_heads = 2, 64, 2
        cfg.vocab_size = 1024
    B, L = 1, 128
    cfg.max_position_embeddings = L
    cfg.dropout = cfg.attn_dropout = 0.0
    model = GPT(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters(),
                          weight_decay=0.01)
    step = TrainStep(model, F.cross_entropy, opt, amp_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (B, L)).astype("int32"))
    return _audit_train_step(step, (ids, ids))


def build_resnet50(scale: str):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.resnet import BasicBlock, BottleneckBlock, ResNet
    from paddle_tpu.nn import functional as F

    paddle.seed(0)
    depth = 18 if scale == "tiny" else 50
    block = BottleneckBlock if depth >= 50 else BasicBlock
    model = ResNet(block, depth)
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    step = TrainStep(model, F.cross_entropy, opt)
    rng = np.random.default_rng(0)
    B, hw = 1, 64
    imgs = paddle.to_tensor(
        rng.normal(size=(B, 3, hw, hw)).astype("float32"))
    labels = paddle.to_tensor(rng.integers(0, 1000, (B,)).astype("int32"))
    return _audit_train_step(step, (imgs, labels))


def build_bert(scale: str):
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.bert import Bert, BertConfig
    from paddle_tpu.nn import functional as F

    paddle.seed(0)
    cfg = BertConfig.tiny() if scale == "tiny" else BertConfig.base()
    B, L = 2, 64
    cfg.max_position_embeddings = max(cfg.max_position_embeddings, L)
    for attr in ("dropout", "hidden_dropout", "attn_dropout",
                 "hidden_dropout_prob", "attention_probs_dropout_prob"):
        if hasattr(cfg, attr):
            setattr(cfg, attr, 0.0)

    class BertCls(nn.Layer):
        def __init__(self):
            super().__init__()
            self.bert = Bert(cfg)
            self.head = nn.Linear(cfg.hidden_size, 2)

        def forward(self, ids):
            _, pooled = self.bert(ids)
            return self.head(pooled)

    model = BertCls()
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = TrainStep(model, F.cross_entropy, opt, amp_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (B, L)).astype("int32"))
    labels = paddle.to_tensor(rng.integers(0, 2, (B,)).astype("int32"))
    return _audit_train_step(step, (ids, labels))


def build_gpt2_decode(scale: str):
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models.gpt import GPT, GPTConfig

    paddle.seed(0)
    # the bench gpt2_decode CI config: real paged-attention program
    # structure (page pools, block-table gathers, donated cache)
    hidden = 64 if scale == "tiny" else 128
    cfg = GPTConfig(vocab_size=1024 if scale == "tiny" else 8192,
                    max_position_embeddings=512, hidden_size=hidden,
                    num_layers=2, num_heads=4,
                    dropout=0.0, attn_dropout=0.0)
    model = GPT(cfg)
    model.eval()
    eng = ServingEngine(model, max_batch=4, max_len=160, page_size=8,
                        name="gpt2_decode_audit")
    return eng.audit(emit=False)


MODELS = {
    "gpt2": build_gpt2,
    "resnet50": build_resnet50,
    "bert": build_bert,
    "gpt2_decode": build_gpt2_decode,
}


def run_audits(models, scale: str):
    """[(model, AuditReport | error-string)] for the requested models."""
    results = []
    for name in models:
        try:
            for report in MODELS[name](scale):
                results.append((name, report))
        except Exception as e:  # noqa: BLE001 — reported, exit 2
            results.append((name, f"{type(e).__name__}: {e}"))
    return results


def run_lints() -> int:
    from paddle_tpu.analysis import conventions
    rc = 0
    for lint, violations in conventions.run_all().items():
        status = "clean" if not violations else \
            f"{len(violations)} violation(s)"
        print(f"[{lint}] {status}")
        for v in violations:
            print(f"  {v}")
            rc = 1
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=sorted(MODELS) + ["all"],
                    default="all", help="which program(s) to audit")
    ap.add_argument("--scale", choices=("ci", "tiny"), default="ci",
                    help="ci = real architectures at CPU-feasible batch "
                         "shapes (default); tiny = shrunken smoke models")
    ap.add_argument("--fail-on", choices=("high", "medium", "low"),
                    default=None, dest="fail_on",
                    help="exit 1 when any finding at/above this severity "
                         "is present (the CI gate uses high)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of the text table")
    ap.add_argument("--lint", action="store_true",
                    help="run the framework convention lints instead of "
                         "the program audits")
    args = ap.parse_args(argv)

    if args.lint:
        return run_lints()

    models = sorted(MODELS) if args.model == "all" else [args.model]
    results = run_audits(models, args.scale)

    errors = [(m, r) for m, r in results if isinstance(r, str)]
    reports = [(m, r) for m, r in results if not isinstance(r, str)]

    gated = 0
    if args.fail_on:
        gated = sum(len(r.by_severity(args.fail_on)) for _, r in reports)

    if args.json:
        doc = {"scale": args.scale,
               "reports": [dict(model=m, **r.to_dict())
                           for m, r in reports],
               "errors": [{"model": m, "error": e} for m, e in errors]}
        if args.fail_on:
            doc["fail_on"] = args.fail_on
            doc["gated_findings"] = gated
        print(json.dumps(doc, indent=2))
    else:
        for m, r in reports:
            print(r.render())
        for m, e in errors:
            print(f"{m}: AUDIT FAILED — {e}", file=sys.stderr)
        if args.fail_on:
            print(f"gate --fail-on={args.fail_on}: {gated} finding(s) "
                  f"at/above threshold")

    if errors:
        return 2
    return 1 if gated else 0


if __name__ == "__main__":
    sys.exit(main())
