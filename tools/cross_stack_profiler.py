#!/usr/bin/env python
"""Cross-stack profiler merge — combine per-rank profiler traces into one
timeline + an aggregated op summary.

Reference: `tools/CrossStackProfiler/` (`CspReporter.py:66` merges per-rank
DCGM/net/op-profile readers into grouped chrome traces, aligning clocks via
a shared time file). The TPU translation: every rank of a
`paddle.distributed.launch` job exports a chrome trace
(`paddle_tpu.profiler.Profiler.export`); this tool merges them into a
single chrome://tracing JSON with one process lane per rank (clock-aligned
to each rank's first event, the `_set_timeInfo` role) and reports per-op
aggregate statistics across ranks.

CLI:
    python tools/cross_stack_profiler.py --trace_dir LOGDIR --out merged.json
where LOGDIR holds `rank_<i>.json` traces (any *.json works; rank inferred
from the filename's trailing integer, else file order).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
from collections import defaultdict
from typing import Dict, Iterable, List, Tuple


def _rank_of(path: str, fallback: int) -> int:
    m = re.search(r"(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else fallback


def load_rank_traces(trace_dir_or_files) -> Dict[int, dict]:
    """{rank: chrome-trace dict} from a directory or explicit file list."""
    if isinstance(trace_dir_or_files, (list, tuple)):
        files = list(trace_dir_or_files)
    else:
        files = sorted(glob.glob(os.path.join(trace_dir_or_files, "*.json")))
    if not files:
        raise FileNotFoundError(f"no trace .json files in {trace_dir_or_files}")
    out = {}
    sources = {}
    for i, f in enumerate(files):
        rank = _rank_of(f, i)
        if rank in out:
            raise ValueError(
                f"rank {rank} inferred for both {sources[rank]!r} and "
                f"{f!r} — rename the trace files so each carries a unique "
                "trailing rank number")
        with open(f) as fh:
            out[rank] = json.load(fh)
        sources[rank] = f
    return out


def merge_traces(traces: Dict[int, dict], align: bool = True) -> dict:
    """One chrome trace with a process lane per rank.

    `align=True` subtracts each rank's first-event timestamp so lanes start
    together (ranks have independent host clocks — the reference aligns via
    `time.txt` prefixes, CspReporter._set_timeInfo)."""
    merged: List[dict] = []
    for rank in sorted(traces):
        events = traces[rank].get("traceEvents", [])
        t0 = min((e["ts"] for e in events if "ts" in e), default=0.0) \
            if align else 0.0
        merged.append({"ph": "M", "name": "process_name", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        for e in events:
            e2 = dict(e)
            e2["pid"] = rank
            if align and "ts" in e2:
                e2["ts"] = e2["ts"] - t0
            merged.append(e2)
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "metadata": {"producer": "paddle_tpu.tools.cross_stack_profiler",
                         "ranks": sorted(traces)}}


def op_summary(traces: Dict[int, dict]) -> List[dict]:
    """Per-op aggregate across ranks: calls, total/mean/max duration (us),
    per-rank total — the reporter's op table, sorted by total desc."""
    acc: Dict[str, dict] = defaultdict(
        lambda: {"calls": 0, "total_us": 0.0, "max_us": 0.0,
                 "by_rank": defaultdict(float)})
    for rank, tr in traces.items():
        for e in tr.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            a = acc[e["name"]]
            dur = float(e.get("dur", 0.0))
            a["calls"] += 1
            a["total_us"] += dur
            a["max_us"] = max(a["max_us"], dur)
            a["by_rank"][rank] += dur
    rows = []
    for name, a in acc.items():
        rows.append({
            "name": name, "calls": a["calls"],
            "total_us": round(a["total_us"], 3),
            "mean_us": round(a["total_us"] / max(a["calls"], 1), 3),
            "max_us": round(a["max_us"], 3),
            "by_rank": {r: round(v, 3) for r, v in sorted(
                a["by_rank"].items())},
        })
    rows.sort(key=lambda r: -r["total_us"])
    return rows


def format_summary(rows: Iterable[dict]) -> str:
    lines = [f"{'op':<40} {'calls':>7} {'total(us)':>12} {'mean(us)':>10} "
             f"{'max(us)':>10}"]
    for r in rows:
        lines.append(f"{r['name'][:40]:<40} {r['calls']:>7} "
                     f"{r['total_us']:>12.1f} {r['mean_us']:>10.1f} "
                     f"{r['max_us']:>10.1f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace_dir", required=True,
                    help="directory of per-rank chrome traces")
    ap.add_argument("--out", required=True, help="merged trace output path")
    ap.add_argument("--no-align", action="store_true",
                    help="keep raw per-rank clocks")
    ap.add_argument("--summary", action="store_true",
                    help="print the cross-rank op summary table")
    args = ap.parse_args(argv)
    traces = load_rank_traces(args.trace_dir)
    merged = merge_traces(traces, align=not args.no_align)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    print(f"merged {len(traces)} rank traces -> {args.out}")
    if args.summary:
        print(format_summary(op_summary(traces)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
