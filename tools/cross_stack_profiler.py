#!/usr/bin/env python
"""Cross-stack profiler merge — combine per-rank profiler traces into one
timeline + an aggregated op summary.

Reference: `tools/CrossStackProfiler/` (`CspReporter.py:66` merges per-rank
DCGM/net/op-profile readers into grouped chrome traces, aligning clocks via
a shared time file). The TPU translation: every rank of a
`paddle.distributed.launch` job exports a chrome trace
(`paddle_tpu.profiler.Profiler.export`); this tool merges them into a
single chrome://tracing JSON with one process lane per rank (clock-aligned
to each rank's first event, the `_set_timeInfo` role) and reports per-op
aggregate statistics across ranks.

CLI:
    python tools/cross_stack_profiler.py --trace_dir LOGDIR --out merged.json
where LOGDIR holds `rank_<i>.json` traces (any *.json works; rank inferred
from the filename's trailing integer, else file order).

XPlane device lanes: pass `--xplane_dir DIR` holding each rank's
jax.profiler output — either per-rank `*<rank>.trace.json.gz` chrome
exports or per-rank session directories (`rank_<i>/` with the standard
`plugins/profile/<ts>/` layout, e.g. a `/profile` capture's session_dir).
Each rank's backend work lanes (classified by paddle_tpu.profiler.xplane)
are interleaved UNDER that rank's host lane in the merged trace as
`xplane:`-named threads, with both clocks shifted to a common zero (host
spans and device events come from different clocks; first-event alignment
is the same role the reference's `time.txt` prefixes play).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rank_of(path: str, fallback: int) -> int:
    m = re.search(r"(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else fallback


def _rank_of_any(path: str, fallback: int) -> int:
    """Trailing rank integer of a trace file (.json / .trace.json.gz) or a
    per-rank session directory name."""
    base = os.path.basename(path.rstrip(os.sep))
    m = re.search(r"(\d+)(?:\.trace)?\.json(?:\.gz)?$", base)
    if m:
        return int(m.group(1))
    m = re.search(r"(\d+)$", base)
    return int(m.group(1)) if m else fallback


def load_rank_traces(trace_dir_or_files) -> Dict[int, dict]:
    """{rank: chrome-trace dict} from a directory or explicit file list."""
    if isinstance(trace_dir_or_files, (list, tuple)):
        files = list(trace_dir_or_files)
    else:
        files = sorted(glob.glob(os.path.join(trace_dir_or_files, "*.json")))
    if not files:
        raise FileNotFoundError(f"no trace .json files in {trace_dir_or_files}")
    out = {}
    sources = {}
    for i, f in enumerate(files):
        rank = _rank_of(f, i)
        if rank in out:
            raise ValueError(
                f"rank {rank} inferred for both {sources[rank]!r} and "
                f"{f!r} — rename the trace files so each carries a unique "
                "trailing rank number")
        with open(f) as fh:
            out[rank] = json.load(fh)
        sources[rank] = f
    return out


def load_xplane_dir(xplane_dir: str) -> Dict[int, list]:
    """{rank: xplane trace events} from a directory of per-rank chrome
    exports (`*<rank>.trace.json.gz` / `*.json`) or per-rank jax session
    directories (anything `xplane.find_trace_file` can resolve)."""
    from paddle_tpu.profiler import xplane as _xplane
    out: Dict[int, list] = {}
    entries = sorted(os.listdir(xplane_dir)) if os.path.isdir(xplane_dir) \
        else []
    if not entries:
        raise FileNotFoundError(f"no entries in --xplane_dir {xplane_dir!r}")
    i = 0
    for name in entries:
        path = os.path.join(xplane_dir, name)
        trace_path: Optional[str] = None
        if os.path.isdir(path):
            trace_path = _xplane.find_trace_file(path)
        elif name.endswith((".json", ".json.gz")):
            trace_path = path
        if trace_path is None:
            continue
        rank = _rank_of_any(path, i)
        i += 1
        if rank in out:
            raise ValueError(f"rank {rank} inferred for two xplane traces "
                             f"under {xplane_dir!r} — rename so each "
                             f"carries a unique trailing rank number")
        out[rank] = _xplane.load_trace(trace_path).get("traceEvents", [])
    if not out:
        raise FileNotFoundError(
            f"--xplane_dir {xplane_dir!r} holds no parseable traces")
    return out


#: tid base for interleaved device lanes — far above any OS thread id's
#: chance of colliding with a host lane in the same pid row
_XPLANE_TID_BASE = 1 << 24


def xplane_device_lane_events(xevents: list, rank: int,
                              align: bool = True) -> List[dict]:
    """Chrome events for one rank's backend work lanes, re-homed under
    pid=rank with `xplane:`-named synthetic threads and the clock shifted
    so the first work event lands at 0 (matching the host lane's
    first-event alignment)."""
    from paddle_tpu.profiler import xplane as _xplane
    works = _xplane.work_events(xevents)
    if not works:
        return []
    procs, threads = _xplane._lane_meta(xevents)
    t0 = min(e.get("ts", 0.0) for e in works) if align else 0.0
    lane_tid: Dict[Tuple[object, object], int] = {}
    out: List[dict] = []
    for e in works:
        lane = (e.get("pid"), e.get("tid"))
        tid = lane_tid.get(lane)
        if tid is None:
            tid = _XPLANE_TID_BASE + len(lane_tid)
            lane_tid[lane] = tid
            pname = procs.get(lane[0], f"pid {lane[0]}")
            tname = threads.get(lane, f"tid {lane[1]}")
            out.append({"ph": "M", "name": "thread_name", "pid": rank,
                        "tid": tid,
                        "args": {"name": f"xplane:{pname}/{tname}"}})
            out.append({"ph": "M", "name": "thread_sort_index", "pid": rank,
                        "tid": tid, "args": {"sort_index": tid}})
        e2 = dict(e)
        e2["pid"] = rank
        e2["tid"] = tid
        if align and "ts" in e2:
            e2["ts"] = e2["ts"] - t0
        out.append(e2)
    return out


def merge_traces(traces: Dict[int, dict], align: bool = True,
                 xplane: Optional[Dict[int, list]] = None) -> dict:
    """One chrome trace with a process lane per rank.

    `align=True` subtracts each rank's first-event timestamp so lanes start
    together (ranks have independent host clocks — the reference aligns via
    `time.txt` prefixes, CspReporter._set_timeInfo). `xplane` maps rank ->
    that rank's jax trace events; its backend work lanes are interleaved
    under the rank's process row as `xplane:` threads on the same
    shifted-to-zero clock."""
    merged: List[dict] = []
    for rank in sorted(traces):
        events = traces[rank].get("traceEvents", [])
        t0 = min((e["ts"] for e in events if "ts" in e), default=0.0) \
            if align else 0.0
        merged.append({"ph": "M", "name": "process_name", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        for e in events:
            e2 = dict(e)
            e2["pid"] = rank
            if align and "ts" in e2:
                e2["ts"] = e2["ts"] - t0
            merged.append(e2)
        if xplane and rank in xplane:
            merged.extend(xplane_device_lane_events(xplane[rank], rank,
                                                    align=align))
    ranks = sorted(traces)
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "metadata": {"producer": "paddle_tpu.tools.cross_stack_profiler",
                         "ranks": ranks,
                         "xplane_ranks": sorted(xplane) if xplane else []}}


def op_summary(traces: Dict[int, dict]) -> List[dict]:
    """Per-op aggregate across ranks: calls, total/mean/max duration (us),
    per-rank total — the reporter's op table, sorted by total desc."""
    acc: Dict[str, dict] = defaultdict(
        lambda: {"calls": 0, "total_us": 0.0, "max_us": 0.0,
                 "by_rank": defaultdict(float)})
    for rank, tr in traces.items():
        for e in tr.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            a = acc[e["name"]]
            dur = float(e.get("dur", 0.0))
            a["calls"] += 1
            a["total_us"] += dur
            a["max_us"] = max(a["max_us"], dur)
            a["by_rank"][rank] += dur
    rows = []
    for name, a in acc.items():
        rows.append({
            "name": name, "calls": a["calls"],
            "total_us": round(a["total_us"], 3),
            "mean_us": round(a["total_us"] / max(a["calls"], 1), 3),
            "max_us": round(a["max_us"], 3),
            "by_rank": {r: round(v, 3) for r, v in sorted(
                a["by_rank"].items())},
        })
    rows.sort(key=lambda r: -r["total_us"])
    return rows


def format_summary(rows: Iterable[dict]) -> str:
    lines = [f"{'op':<40} {'calls':>7} {'total(us)':>12} {'mean(us)':>10} "
             f"{'max(us)':>10}"]
    for r in rows:
        lines.append(f"{r['name'][:40]:<40} {r['calls']:>7} "
                     f"{r['total_us']:>12.1f} {r['mean_us']:>10.1f} "
                     f"{r['max_us']:>10.1f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace_dir", required=True,
                    help="directory of per-rank chrome traces")
    ap.add_argument("--out", required=True, help="merged trace output path")
    ap.add_argument("--no-align", action="store_true",
                    help="keep raw per-rank clocks")
    ap.add_argument("--summary", action="store_true",
                    help="print the cross-rank op summary table")
    ap.add_argument("--xplane_dir", default=None,
                    help="directory of per-rank jax.profiler traces "
                         "(*<rank>.trace.json.gz or rank_<i>/ session "
                         "dirs); device lanes are interleaved under each "
                         "rank's host lane")
    args = ap.parse_args(argv)
    traces = load_rank_traces(args.trace_dir)
    xplane = load_xplane_dir(args.xplane_dir) if args.xplane_dir else None
    merged = merge_traces(traces, align=not args.no_align, xplane=xplane)
    with open(args.out, "w") as f:
        json.dump(merged, f)
    print(f"merged {len(traces)} rank traces"
          + (f" + {len(xplane)} xplane device traces" if xplane else "")
          + f" -> {args.out}")
    if args.summary:
        print(format_summary(op_summary(traces)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
