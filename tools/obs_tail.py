#!/usr/bin/env python
"""Tail / filter / pretty-print the unified structured event log.

The runtime writes one JSON object per line to `PADDLE_TPU_EVENT_LOG`
(schema: paddle_tpu/profiler/events.py — required ts/kind/host, optional
severity + kind-specific payload). This renders that stream for operators:

    python tools/obs_tail.py events.jsonl                  # whole file
    python tools/obs_tail.py events.jsonl -n 50            # last 50
    python tools/obs_tail.py events.jsonl --kind retrace
    python tools/obs_tail.py events.jsonl --host trainer-1 --min-severity warn
    python tools/obs_tail.py events.jsonl --follow         # live tail
    python tools/obs_tail.py events.jsonl --follow --follow-for 30
    python tools/obs_tail.py events.jsonl --json --kind fleet_straggler
    python tools/obs_tail.py events.jsonl --diagnose       # step_diagnosis
    python tools/obs_tail.py events.jsonl --health         # numerics plane
    python tools/obs_tail.py events.jsonl --controller     # fleet decisions
    python tools/obs_tail.py events.jsonl --serving        # request lifecycle
    python tools/obs_tail.py events.jsonl --slo            # SLO plane
    python tools/obs_tail.py events.jsonl --analysis       # auditor findings
    cat events.jsonl | python tools/obs_tail.py -

`--diagnose` renders `step_diagnosis` events (the runtime's step-slowness
decomposition) as a per-window cost breakdown naming the dominant term;
`--health` renders the training-health events (tensor_health NaN/Inf
attribution, health_alert divergence signals, health_rollback responses,
fleet_health) in an operator-oriented line format; `--serving` renders
the continuous-batching request lifecycle (serving_admission /
serving_eviction: slot, bucket, queue wait, eviction reason, free
pages); `--slo` renders the serving SLO plane (slo_breach excursions —
signal, window quantile vs target — and request_trace per-request phase
breakdowns); `--analysis` renders static program-auditor findings
(analysis_finding: program, check/code, offending param + scope, fix
hint); `--follow-for N`
bounds a live tail to N seconds (scripting/CI). A sink rotated by
`PADDLE_TPU_EVENT_LOG_MAX_MB` is read transparently: `path.N`...`path.1`
siblings stream before `path` in chronological order.

A running job's recent window is also served live over HTTP
(`/events?kind=...` on the ObservabilityServer) — this tool is the
file-based long-horizon view. Lines that do not parse as JSON (torn
writes, interleaved logging) are counted and reported on stderr, never
fatal. Exit 0 on success, 2 on unusable input.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from datetime import datetime
from typing import Iterable, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    # the schema owner — keeps --min-severity ordering in lockstep with
    # what the runtime emits
    from paddle_tpu.profiler.events import SEVERITIES
except Exception:  # standalone copy of the tool, no repo on path
    SEVERITIES = ("debug", "info", "warn", "error")

try:
    from paddle_tpu.profiler.health import HEALTH_EVENT_KINDS as _HK
    HEALTH_KINDS = tuple(_HK) + ("fleet_health",)
except Exception:
    HEALTH_KINDS = ("tensor_health", "health_alert", "health_rollback",
                    "fleet_health")

SERVING_KINDS = ("serving_admission", "serving_eviction")

#: the HA control-plane view: decisions plus the election/fencing
#: lifecycle (who leads at what term, takeovers, fenced stale
#: actuations, and the nobody-leads alarm)
CONTROLLER_KINDS = ("controller_decision", "controller_takeover",
                    "controller_fenced", "fleet_leaderless")

SLO_KINDS = ("slo_breach", "request_trace", "serving_swap",
             "serving_restart")

ANALYSIS_KINDS = ("analysis_finding",)


def rotated_siblings(path: str):
    """Rotated sink files for `path` (see events.py size-based rotation:
    `path.1` is the newest rotated file), oldest first — so reading
    siblings then `path` yields one chronological stream."""
    sibs = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        sibs.append(f"{path}.{i}")
        i += 1
    return list(reversed(sibs))


def read_lines(path: str):
    """All lines of `path`, transparently prefixed with its rotated
    siblings (a rotated long-horizon log reads as one stream)."""
    lines = []
    for p in rotated_siblings(path) + [path]:
        try:
            with open(p) as f:
                lines.extend(f.readlines())
        except OSError:
            continue
    return lines


def parse_lines(lines: Iterable[str]):
    """(events, bad_line_count) from raw JSONL lines."""
    events, bad = [], 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            bad += 1
            continue
        if isinstance(rec, dict) and "kind" in rec:
            events.append(rec)
        else:
            bad += 1
    return events, bad


def event_matches(rec: dict, kind, host: Optional[str],
                  min_severity: Optional[str], since_ts: float = 0.0) -> bool:
    """`kind` may be one kind name or a tuple/set of them (--health)."""
    if kind:
        if isinstance(kind, str):
            if rec.get("kind") != kind:
                return False
        elif rec.get("kind") not in kind:
            return False
    if host and rec.get("host") != host:
        return False
    if min_severity:
        sev = rec.get("severity", "info")
        if sev in SEVERITIES and \
                SEVERITIES.index(sev) < SEVERITIES.index(min_severity):
            return False
    if since_ts and rec.get("ts", 0) < since_ts:
        return False
    return True


def scope_slo_decisions(events, args):
    """--slo without --controller: of the controller_decision stream,
    only the serving_* policies belong in the SLO view."""
    if not getattr(args, "slo", False) or getattr(args, "controller",
                                                  False):
        return events
    return [e for e in events
            if e.get("kind") != "controller_decision"
            or str(e.get("policy", "")).startswith("serving")]


def format_event(rec: dict) -> str:
    """One aligned human line: time, severity, kind, host, then the
    kind-specific payload as key=value pairs."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    sev = rec.get("severity", "info")
    extras = " ".join(
        f"{k}={json.dumps(v) if isinstance(v, (dict, list)) else v}"
        for k, v in rec.items()
        if k not in ("ts", "kind", "host", "severity"))
    return (f"{when} {sev:<5} {rec.get('kind', '?'):<20} "
            f"{rec.get('host', '?'):<16} {extras}")


def format_diagnosis(rec: dict) -> str:
    """One step_diagnosis event as a cost breakdown line: dominant term
    first with its share of the wall, then every nonzero term."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    terms = rec.get("terms") or {}
    dom = rec.get("dominant", "?")
    frac = rec.get("dominant_frac")
    frac_s = f" ({100 * frac:.0f}% of wall)" if isinstance(
        frac, (int, float)) else ""
    parts = " | ".join(
        f"{k}={1000 * v:.1f}ms"
        for k, v in sorted(terms.items(), key=lambda kv: -kv[1])
        if isinstance(v, (int, float)) and v > 0) or "no nonzero terms"
    step = f" step {rec['step']}" if "step" in rec else ""
    return (f"{when} {rec.get('host', '?'):<16}{step} "
            f"wall {1000 * rec.get('wall_s', 0.0):.1f}ms over "
            f"{rec.get('steps', '?')} step(s): dominant={dom}{frac_s}  "
            f"[{parts}]")


def format_health(rec: dict) -> str:
    """One health event as an operator line: what went bad, where, and
    what the runtime did about it."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    kind = rec.get("kind", "?")
    step = f" step {rec['step']}" if "step" in rec else ""
    if kind == "tensor_health":
        where = rec.get("layer") or ",".join(rec.get("bad_groups") or []) \
            or "?"
        what = rec.get("bad_kind") or "nonfinite"
        op = f" op={rec['op']}" if rec.get("op") else ""
        detail = f"{what} in {where}{op} (src={rec.get('src', '?')})"
    elif kind == "health_alert":
        detail = f"{rec.get('signal', '?')}"
        for k in ("loss", "z", "grad_norm", "reason"):
            if rec.get(k) is not None:
                detail += f" {k}={rec[k]}"
    elif kind == "health_rollback":
        detail = (f"restored checkpoint step {rec.get('restored_step')} "
                  f"(reason={rec.get('reason')}, "
                  f"rollback #{rec.get('rollbacks')})")
    elif kind == "fleet_health":
        detail = (f"host {rec.get('unhealthy')} went "
                  f"{rec.get('status', '?')}")
    else:
        return format_event(rec)
    return (f"{when} {rec.get('severity', 'info'):<5} {kind:<20} "
            f"{rec.get('host', '?'):<16}{step} {detail}")


def format_controller(rec: dict) -> str:
    """One controller_decision event as an operator line: which policy
    fired, on what evidence, what it did, and whether it acted."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    kind = rec.get("kind", "controller_decision")
    if kind == "controller_takeover":
        detail = (f"leader={rec.get('leader', '?')} term={rec.get('term')} "
                  f"took over ({rec.get('reason', '?')})")
        return (f"{when} {rec.get('severity', 'warn'):<5} "
                f"{'takeover':<20} {rec.get('host', '?'):<16} {detail}")
    if kind == "controller_fenced":
        detail = (f"stale term {rec.get('term')} < current "
                  f"{rec.get('current_term')} — dropped "
                  f"{rec.get('action', rec.get('policy', '?'))}")
        if rec.get("target"):
            detail += f" target={rec['target']}"
        return (f"{when} {rec.get('severity', 'warn'):<5} "
                f"{'fenced':<20} {rec.get('host', '?'):<16} {detail}")
    if kind == "fleet_leaderless":
        detail = (f"no live leader for {rec.get('silent_s')}s "
                  f"(ttl={rec.get('ttl_s')}s; last lease: "
                  f"leader={rec.get('leader', '?')} term={rec.get('term')})")
        return (f"{when} {rec.get('severity', 'warn'):<5} "
                f"{'leaderless':<20} {rec.get('host', '?'):<16} {detail}")
    policy = rec.get("policy", "?")
    outcome = rec.get("outcome", "?")
    if rec.get("action") == "relaunch_observed":
        detail = (f"decision #{rec.get('decision')} fleet resumed: "
                  f"relaunch→first-step "
                  f"{rec.get('relaunch_to_first_step_s')}s")
    else:
        ev = rec.get("evidence") or {}
        bits = [f"action={rec.get('action', '?')}"]
        if rec.get("target"):
            bits.append(f"target={rec['target']}")
        if rec.get("np") is not None:
            bits.append(f"np→{rec['np']}")
        for k in ("windows", "p50_s", "diverged", "held_s", "ready_age_s"):
            if ev.get(k) is not None:
                v = ev[k]
                bits.append(f"{k}={json.dumps(v) if isinstance(v, (list, dict)) else v}")
        if rec.get("dry_run"):
            bits.append("DRY-RUN")
        detail = (f"decision #{rec.get('decision')} "
                  f"[{outcome}] " + " ".join(bits))
    return (f"{when} {rec.get('severity', 'info'):<5} "
            f"{policy:<20} {rec.get('host', '?'):<16} {detail}")


def format_serving(rec: dict) -> str:
    """One serving lifecycle event as an operator line: who entered/left
    the decode batch, why, and what it cost."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    kind = rec.get("kind", "?")
    rid = rec.get("request", "?")
    if kind == "serving_admission":
        detail = (f"request {rid} -> slot {rec.get('slot')} "
                  f"(prompt {rec.get('prompt_len')} -> bucket "
                  f"{rec.get('bucket')}, waited "
                  f"{rec.get('queue_wait_s')}s")
        if rec.get("preemptions"):
            detail += f", preemptions={rec['preemptions']}"
        detail += f", free_pages={rec.get('free_pages')})"
    elif kind == "serving_eviction":
        detail = (f"request {rid} left the batch: "
                  f"{rec.get('reason', '?')} after "
                  f"{rec.get('generated')} token(s), free_pages="
                  f"{rec.get('free_pages')}")
    else:
        return format_event(rec)
    return (f"{when} {rec.get('severity', 'info'):<5} {kind:<20} "
            f"{rec.get('host', '?'):<16} {detail}")


def format_slo(rec: dict) -> str:
    """One SLO-plane event as an operator line: which signal left (or
    which request finished under) what latency budget."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    kind = rec.get("kind", "?")
    if kind == "slo_breach":
        val = rec.get("value")
        tgt = rec.get("target")
        val_s = f"{1000 * val:.1f}ms" if isinstance(val, (int, float)) \
            else "?"
        tgt_s = f"{1000 * tgt:.1f}ms" if isinstance(tgt, (int, float)) \
            else "?"
        detail = (f"{rec.get('signal', '?')} "
                  f"{rec.get('quantile', 'p99')}={val_s} breached target "
                  f"{tgt_s} over {rec.get('window', '?')} sample(s) "
                  f"(model {rec.get('model', '?')}; one event per "
                  f"excursion, re-arms on recovery)")
    elif kind == "request_trace":
        phases = rec.get("phases") or {}
        parts = " | ".join(
            f"{k}={1000 * v:.1f}ms"
            for k, v in sorted(phases.items(), key=lambda kv: -kv[1])
            if isinstance(v, (int, float)) and v > 0) or "no phases"
        e2e = rec.get("e2e_s")
        e2e_s = f"{1000 * e2e:.1f}ms" if isinstance(e2e, (int, float)) \
            else "?"
        detail = (f"trace {rec.get('trace_id', '?')} request "
                  f"{rec.get('rid', '?')} {rec.get('finish_reason', '?')} "
                  f"e2e {e2e_s}")
        if rec.get("preemptions"):
            detail += f" preemptions={rec['preemptions']}"
        detail += f"  [{parts}]"
    elif kind == "serving_swap":
        action = rec.get("action", "?")
        model = rec.get("model", "?")
        if action in ("swap", "rollback"):
            pause = rec.get("pause_s")
            pause_s = f"{1000 * pause:.1f}ms" if isinstance(
                pause, (int, float)) else "?"
            detail = (f"{action} {model} weights step "
                      f"{rec.get('from_step')} -> {rec.get('to_step')} "
                      f"(pause {pause_s}, {rec.get('in_flight', 0)} "
                      f"in-flight, source {rec.get('source', '?')})")
        elif action == "reject":
            detail = (f"canary REJECTED step {rec.get('to_step')} for "
                      f"{model}: cand_ppl={rec.get('cand_ppl')} vs "
                      f"live_ppl={rec.get('live_ppl')} "
                      f"(tol {rec.get('tol')})")
        elif action == "fail":
            detail = (f"load of step {rec.get('to_step')} for {model} "
                      f"failed ({rec.get('error')}), attempt "
                      f"#{rec.get('attempts')}"
                      + (", BLACKLISTED" if rec.get("blacklisted")
                         else ""))
        elif action == "halt":
            detail = (f"hot-swap HALTED for {model}: "
                      f"{rec.get('reason', '?')} after "
                      f"{rec.get('rollbacks')} rollback(s) — manual "
                      f"re-arm required")
        else:  # stage
            detail = (f"{action} {model} -> step {rec.get('to_step')} "
                      f"(source {rec.get('source', '?')})")
    elif kind == "serving_restart":
        detail = (f"engine {rec.get('model', '?')} restarted "
                  f"({rec.get('reason', '?')}): {rec.get('requeued')} "
                  f"in-flight requeued, {rec.get('leaked_pages')} "
                  f"leaked page(s), loop "
                  f"{'relaunched' if rec.get('restarted_thread') else 'left stopped'}")
    else:
        return format_event(rec)
    return (f"{when} {rec.get('severity', 'info'):<5} {kind:<20} "
            f"{rec.get('host', '?'):<16} {detail}")


def format_analysis(rec: dict) -> str:
    """One analysis_finding event as an operator line: which program,
    which check fired, where, and the fix hint."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    sev = rec.get("finding_severity", rec.get("severity", "?"))
    where = f"{rec.get('program', '?')}[{rec.get('entry', '?')}]"
    detail = f"{rec.get('check', '?')}/{rec.get('code', '?')}"
    if rec.get("param"):
        detail += f" at {rec['param']}"
    if rec.get("scope"):
        detail += f" (scope {rec['scope']})"
    detail += f": {rec.get('message', '')}"
    if rec.get("fix_hint"):
        detail += f" — fix: {rec['fix_hint']}"
    return (f"{when} {sev:<6} {where:<28} "
            f"{rec.get('host', '?'):<16} {detail}")


def _emit(events, as_json: bool, out=None, diagnose: bool = False,
          health: bool = False, controller: bool = False,
          serving: bool = False, analysis: bool = False,
          slo: bool = False):
    out = out if out is not None else sys.stdout  # resolve at call time
    for rec in events:
        if as_json:
            line = json.dumps(rec)
        elif diagnose and rec.get("kind") == "step_diagnosis":
            line = format_diagnosis(rec)
        elif health and rec.get("kind") in HEALTH_KINDS:
            line = format_health(rec)
        elif controller and rec.get("kind") in CONTROLLER_KINDS:
            line = format_controller(rec)
        elif serving and rec.get("kind") in SERVING_KINDS:
            line = format_serving(rec)
        elif analysis and rec.get("kind") in ANALYSIS_KINDS:
            line = format_analysis(rec)
        elif slo and rec.get("kind") in SLO_KINDS:
            line = format_slo(rec)
        elif slo and rec.get("kind") == "controller_decision":
            # --slo pulls in the controller's serving actions (shed,
            # restart, swap rollback) so one view tells the whole
            # breach -> reaction story
            line = format_controller(rec)
        else:
            line = format_event(rec)
        out.write(line + "\n")
    out.flush()


def follow(path: str, args, poll_s: float = 0.5,
           max_s: Optional[float] = None):
    """Live tail: print matching events appended after startup (plus the
    initial -n window). Ctrl-C exits cleanly; `max_s` bounds the tail
    (--follow-for) so scripted runs terminate on their own."""
    t0 = time.monotonic()
    diagnose = getattr(args, "diagnose", False)
    health = getattr(args, "health", False)
    controller = getattr(args, "controller", False)
    serving = getattr(args, "serving", False)
    analysis = getattr(args, "analysis", False)
    slo = getattr(args, "slo", False)
    # open the live file FIRST and read the backlog through the same
    # handle: reading a snapshot and then seeking a fresh handle to EOF
    # would silently drop events appended in between
    f = open(path)
    lines = []
    for p in rotated_siblings(path):
        try:
            with open(p) as sib:
                lines.extend(sib.readlines())
        except OSError:
            continue
    lines.extend(f.readlines())  # leaves f at EOF for the tail loop
    events, _ = parse_lines(lines)
    window = scope_slo_decisions(
        [e for e in events
         if event_matches(e, args.kind, args.host,
                          args.min_severity, args.since_ts)], args)
    _emit(window[-args.n:] if args.n else window, args.json,
          diagnose=diagnose, health=health, controller=controller,
          serving=serving, analysis=analysis, slo=slo)
    try:
        while True:
            if max_s is not None and time.monotonic() - t0 >= max_s:
                return 0
            line = f.readline()
            if not line:
                # the sink may have rotated underneath us (path is now a
                # fresh file): reopen when the inode changed
                try:
                    if os.stat(path).st_ino != os.fstat(f.fileno()).st_ino:
                        f.close()
                        f = open(path)
                        continue
                except OSError:
                    pass
                time.sleep(poll_s)
                continue
            recs, _ = parse_lines([line])
            _emit(scope_slo_decisions(
                      [r for r in recs
                       if event_matches(r, args.kind, args.host,
                                        args.min_severity,
                                        args.since_ts)], args),
                  args.json, diagnose=diagnose, health=health,
                  controller=controller, serving=serving,
                  analysis=analysis, slo=slo)
    except KeyboardInterrupt:
        return 0
    finally:
        f.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="event JSONL file (PADDLE_TPU_EVENT_LOG), "
                                 "or - for stdin")
    ap.add_argument("-n", type=int, default=0,
                    help="only the last N matching events (0 = all)")
    ap.add_argument("--kind", default=None,
                    help="only this event kind (retrace, barrier_abort, "
                         "fleet_straggler, ...)")
    ap.add_argument("--host", default=None, help="only this host id")
    ap.add_argument("--min-severity", default=None, choices=SEVERITIES,
                    help="drop events below this severity")
    ap.add_argument("--since-sec", type=float, default=0.0,
                    help="only events newer than this many seconds ago")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing the file for new events")
    ap.add_argument("--follow-for", type=float, default=None, metavar="SEC",
                    help="with --follow: stop after this many seconds "
                         "(default: until Ctrl-C)")
    ap.add_argument("--diagnose", action="store_true",
                    help="show step_diagnosis events as a per-window cost "
                         "breakdown (implies --kind step_diagnosis unless "
                         "--kind is given)")
    ap.add_argument("--health", action="store_true",
                    help="show training-health events (tensor_health, "
                         "health_alert, health_rollback, fleet_health) "
                         "with an operator-oriented rendering; filters to "
                         "those kinds unless --kind is given")
    ap.add_argument("--controller", action="store_true",
                    help="show the HA control plane (controller_decision: "
                         "policy, evidence, action, outcome; "
                         "controller_takeover: leader id, term, reason; "
                         "controller_fenced: stale-term actuation dropped; "
                         "fleet_leaderless: no live lease) with an "
                         "operator-oriented rendering; filters to those "
                         "kinds unless --kind is given")
    ap.add_argument("--serving", action="store_true",
                    help="show continuous-batching serving events "
                         "(serving_admission / serving_eviction: slot, "
                         "bucket, queue wait, eviction reason, free "
                         "pages) with an operator-oriented rendering; "
                         "filters to those kinds unless --kind is given")
    ap.add_argument("--slo", action="store_true",
                    help="show the serving SLO plane (slo_breach: signal, "
                         "window quantile vs target; request_trace: "
                         "per-request phase breakdown; serving_swap / "
                         "serving_restart and the controller's serving_* "
                         "decisions: the self-healing reactions) with an "
                         "operator-oriented rendering; filters to those "
                         "kinds unless --kind is given")
    ap.add_argument("--analysis", action="store_true",
                    help="show static program-auditor findings "
                         "(analysis_finding: program, check, offending "
                         "param/scope, fix hint) with an "
                         "operator-oriented rendering; filters to that "
                         "kind unless --kind is given")
    ap.add_argument("--json", action="store_true",
                    help="emit matching events as raw JSONL instead of the "
                         "human format")
    args = ap.parse_args(argv)
    args.since_ts = time.time() - args.since_sec if args.since_sec else 0.0
    if args.diagnose and args.kind is None:
        args.kind = "step_diagnosis"
    if args.health and args.kind is None:
        args.kind = HEALTH_KINDS
    elif args.health and args.kind == "step_diagnosis" and args.diagnose:
        # --health --diagnose together: health events AND the step
        # decomposition in one stream
        args.kind = HEALTH_KINDS + ("step_diagnosis",)
    if args.controller:
        # composes with --health/--diagnose: the control plane joins
        # the stream (decisions + election/fencing lifecycle)
        if args.kind is None:
            args.kind = CONTROLLER_KINDS
        elif isinstance(args.kind, tuple):
            args.kind = args.kind + CONTROLLER_KINDS
        elif args.kind not in CONTROLLER_KINDS:
            args.kind = (args.kind,) + CONTROLLER_KINDS
    if args.serving:
        # composes with the other operator views the same way
        if args.kind is None:
            args.kind = SERVING_KINDS
        elif isinstance(args.kind, tuple):
            args.kind = args.kind + SERVING_KINDS
        else:
            args.kind = (args.kind,) + SERVING_KINDS
    if args.slo:
        # the SLO view includes the controller's serving actions
        # (policy serving_*) so breach and reaction read as one stream;
        # non-serving decisions stay out unless --controller is given
        slo_kinds = SLO_KINDS + ("controller_decision",)
        if args.kind is None:
            args.kind = slo_kinds
        elif isinstance(args.kind, tuple):
            args.kind = args.kind + slo_kinds
        else:
            args.kind = (args.kind,) + slo_kinds
    if args.analysis:
        if args.kind is None:
            args.kind = ANALYSIS_KINDS
        elif isinstance(args.kind, tuple):
            args.kind = args.kind + ANALYSIS_KINDS
        else:
            args.kind = (args.kind,) + ANALYSIS_KINDS

    if args.follow:
        if args.path == "-":
            print("obs_tail: --follow needs a file path", file=sys.stderr)
            return 2
        try:
            with open(args.path):
                pass
        except OSError as e:
            print(f"obs_tail: {e}", file=sys.stderr)
            return 2
        return follow(args.path, args, max_s=args.follow_for) or 0

    if args.path == "-":
        lines = sys.stdin.readlines()
    else:
        try:
            # probe the LIVE file loudly (missing OR unreadable must exit
            # 2, not read as an empty-and-healthy log); rotated siblings
            # stay best-effort
            with open(args.path):
                pass
        except OSError as e:
            print(f"obs_tail: {e}", file=sys.stderr)
            return 2
        lines = read_lines(args.path)  # rotated siblings included
    events, bad = parse_lines(lines)
    if bad:
        print(f"obs_tail: skipped {bad} unparseable line(s)",
              file=sys.stderr)
    if not events and bad:
        return 2
    matching = scope_slo_decisions(
        [e for e in events
         if event_matches(e, args.kind, args.host,
                          args.min_severity, args.since_ts)], args)
    _emit(matching[-args.n:] if args.n else matching, args.json,
          diagnose=args.diagnose, health=args.health,
          controller=args.controller, serving=args.serving,
          analysis=args.analysis, slo=args.slo)
    return 0


if __name__ == "__main__":
    sys.exit(main())
