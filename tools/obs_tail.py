#!/usr/bin/env python
"""Tail / filter / pretty-print the unified structured event log.

The runtime writes one JSON object per line to `PADDLE_TPU_EVENT_LOG`
(schema: paddle_tpu/profiler/events.py — required ts/kind/host, optional
severity + kind-specific payload). This renders that stream for operators:

    python tools/obs_tail.py events.jsonl                  # whole file
    python tools/obs_tail.py events.jsonl -n 50            # last 50
    python tools/obs_tail.py events.jsonl --kind retrace
    python tools/obs_tail.py events.jsonl --host trainer-1 --min-severity warn
    python tools/obs_tail.py events.jsonl --follow         # live tail
    python tools/obs_tail.py events.jsonl --follow --follow-for 30
    python tools/obs_tail.py events.jsonl --json --kind fleet_straggler
    python tools/obs_tail.py events.jsonl --diagnose       # step_diagnosis
    cat events.jsonl | python tools/obs_tail.py -

`--diagnose` renders `step_diagnosis` events (the runtime's step-slowness
decomposition) as a per-window cost breakdown naming the dominant term;
`--follow-for N` bounds a live tail to N seconds (scripting/CI).

A running job's recent window is also served live over HTTP
(`/events?kind=...` on the ObservabilityServer) — this tool is the
file-based long-horizon view. Lines that do not parse as JSON (torn
writes, interleaved logging) are counted and reported on stderr, never
fatal. Exit 0 on success, 2 on unusable input.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from datetime import datetime
from typing import Iterable, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    # the schema owner — keeps --min-severity ordering in lockstep with
    # what the runtime emits
    from paddle_tpu.profiler.events import SEVERITIES
except Exception:  # standalone copy of the tool, no repo on path
    SEVERITIES = ("debug", "info", "warn", "error")


def parse_lines(lines: Iterable[str]):
    """(events, bad_line_count) from raw JSONL lines."""
    events, bad = [], 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            bad += 1
            continue
        if isinstance(rec, dict) and "kind" in rec:
            events.append(rec)
        else:
            bad += 1
    return events, bad


def event_matches(rec: dict, kind: Optional[str], host: Optional[str],
                  min_severity: Optional[str], since_ts: float = 0.0) -> bool:
    if kind and rec.get("kind") != kind:
        return False
    if host and rec.get("host") != host:
        return False
    if min_severity:
        sev = rec.get("severity", "info")
        if sev in SEVERITIES and \
                SEVERITIES.index(sev) < SEVERITIES.index(min_severity):
            return False
    if since_ts and rec.get("ts", 0) < since_ts:
        return False
    return True


def format_event(rec: dict) -> str:
    """One aligned human line: time, severity, kind, host, then the
    kind-specific payload as key=value pairs."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    sev = rec.get("severity", "info")
    extras = " ".join(
        f"{k}={json.dumps(v) if isinstance(v, (dict, list)) else v}"
        for k, v in rec.items()
        if k not in ("ts", "kind", "host", "severity"))
    return (f"{when} {sev:<5} {rec.get('kind', '?'):<20} "
            f"{rec.get('host', '?'):<16} {extras}")


def format_diagnosis(rec: dict) -> str:
    """One step_diagnosis event as a cost breakdown line: dominant term
    first with its share of the wall, then every nonzero term."""
    ts = rec.get("ts")
    try:
        when = datetime.fromtimestamp(float(ts)).strftime("%H:%M:%S.%f")[:-3]
    except (TypeError, ValueError, OSError):
        when = "??:??:??.???"
    terms = rec.get("terms") or {}
    dom = rec.get("dominant", "?")
    frac = rec.get("dominant_frac")
    frac_s = f" ({100 * frac:.0f}% of wall)" if isinstance(
        frac, (int, float)) else ""
    parts = " | ".join(
        f"{k}={1000 * v:.1f}ms"
        for k, v in sorted(terms.items(), key=lambda kv: -kv[1])
        if isinstance(v, (int, float)) and v > 0) or "no nonzero terms"
    step = f" step {rec['step']}" if "step" in rec else ""
    return (f"{when} {rec.get('host', '?'):<16}{step} "
            f"wall {1000 * rec.get('wall_s', 0.0):.1f}ms over "
            f"{rec.get('steps', '?')} step(s): dominant={dom}{frac_s}  "
            f"[{parts}]")


def _emit(events, as_json: bool, out=None, diagnose: bool = False):
    out = out if out is not None else sys.stdout  # resolve at call time
    for rec in events:
        if as_json:
            line = json.dumps(rec)
        elif diagnose and rec.get("kind") == "step_diagnosis":
            line = format_diagnosis(rec)
        else:
            line = format_event(rec)
        out.write(line + "\n")
    out.flush()


def follow(path: str, args, poll_s: float = 0.5,
           max_s: Optional[float] = None):
    """Live tail: print matching events appended after startup (plus the
    initial -n window). Ctrl-C exits cleanly; `max_s` bounds the tail
    (--follow-for) so scripted runs terminate on their own."""
    t0 = time.monotonic()
    diagnose = getattr(args, "diagnose", False)
    with open(path) as f:
        events, _ = parse_lines(f)
        window = [e for e in events
                  if event_matches(e, args.kind, args.host,
                                   args.min_severity, args.since_ts)]
        _emit(window[-args.n:] if args.n else window, args.json,
              diagnose=diagnose)
        try:
            while True:
                if max_s is not None and time.monotonic() - t0 >= max_s:
                    return 0
                line = f.readline()
                if not line:
                    time.sleep(poll_s)
                    continue
                recs, _ = parse_lines([line])
                _emit([r for r in recs
                       if event_matches(r, args.kind, args.host,
                                        args.min_severity, args.since_ts)],
                      args.json, diagnose=diagnose)
        except KeyboardInterrupt:
            return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="event JSONL file (PADDLE_TPU_EVENT_LOG), "
                                 "or - for stdin")
    ap.add_argument("-n", type=int, default=0,
                    help="only the last N matching events (0 = all)")
    ap.add_argument("--kind", default=None,
                    help="only this event kind (retrace, barrier_abort, "
                         "fleet_straggler, ...)")
    ap.add_argument("--host", default=None, help="only this host id")
    ap.add_argument("--min-severity", default=None, choices=SEVERITIES,
                    help="drop events below this severity")
    ap.add_argument("--since-sec", type=float, default=0.0,
                    help="only events newer than this many seconds ago")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing the file for new events")
    ap.add_argument("--follow-for", type=float, default=None, metavar="SEC",
                    help="with --follow: stop after this many seconds "
                         "(default: until Ctrl-C)")
    ap.add_argument("--diagnose", action="store_true",
                    help="show step_diagnosis events as a per-window cost "
                         "breakdown (implies --kind step_diagnosis unless "
                         "--kind is given)")
    ap.add_argument("--json", action="store_true",
                    help="emit matching events as raw JSONL instead of the "
                         "human format")
    args = ap.parse_args(argv)
    args.since_ts = time.time() - args.since_sec if args.since_sec else 0.0
    if args.diagnose and args.kind is None:
        args.kind = "step_diagnosis"

    if args.follow:
        if args.path == "-":
            print("obs_tail: --follow needs a file path", file=sys.stderr)
            return 2
        if not os.path.exists(args.path):
            print(f"obs_tail: {args.path}: no such file", file=sys.stderr)
            return 2
        return follow(args.path, args, max_s=args.follow_for) or 0

    try:
        lines = sys.stdin.readlines() if args.path == "-" \
            else open(args.path).readlines()
    except OSError as e:
        print(f"obs_tail: {e}", file=sys.stderr)
        return 2
    events, bad = parse_lines(lines)
    if bad:
        print(f"obs_tail: skipped {bad} unparseable line(s)",
              file=sys.stderr)
    if not events and bad:
        return 2
    matching = [e for e in events
                if event_matches(e, args.kind, args.host,
                                 args.min_severity, args.since_ts)]
    _emit(matching[-args.n:] if args.n else matching, args.json,
          diagnose=args.diagnose)
    return 0


if __name__ == "__main__":
    sys.exit(main())
