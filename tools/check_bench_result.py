#!/usr/bin/env python
"""Benchmark regression gate — compare a bench.py JSON result against a
baseline and fail on regressions.

Reference: `tools/check_op_benchmark_result.py` (the op-benchmark CI gate:
parse the PR run and the develop-branch logs, alarm when speed or accuracy
regress past a threshold). Here the artifacts are the driver's
`BENCH_r{N}.json` files / a raw `python bench.py` output line: every config
with a throughput-like metric is compared, and a relative drop beyond
--threshold (default 5%) fails the gate. Higher-is-better metrics only —
step_time_ms is derived from them and would double-count.

The gate also validates the current round's `observability` sections
against the runtime's schema contracts: every `step_records` entry must
pass `profiler.monitor.validate_step_record` and every `events_tail`/
`events` entry must pass `profiler.events.validate_event` (top-level and
per-config blocks alike) — a bench emitting malformed telemetry fails like
a perf regression does.

CLI:
    python tools/check_bench_result.py --baseline BENCH_r04.json \
        --current BENCH_r05.json [--threshold 0.05] [--no-obs-check]
Exit code 0 = no regression, 1 = regression/invalid observability,
2 = unusable inputs.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# throughput metrics, higher is better
_METRICS = ("tokens_per_sec_chip", "samples_per_sec_chip",
            "examples_per_sec")


def _load(path: str) -> dict:
    """Accept a raw `python bench.py` line, a pretty-printed bench object,
    or a driver BENCH_r{N}.json wrapper (bench line embedded in `tail`)."""
    with open(path) as f:
        txt = f.read().strip()
    try:
        doc = json.loads(txt)
        if isinstance(doc, dict):
            if "configs" in doc or "value" in doc:
                return doc
            tail = doc.get("tail")
            if isinstance(tail, str):
                txt = tail  # fall through to line scanning below
    except json.JSONDecodeError:
        pass
    for line in reversed(txt.splitlines()):
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            return json.loads(line)
    raise ValueError(f"{path}: no bench JSON object found")


def _configs(doc: dict) -> Dict[str, dict]:
    cfgs = doc.get("configs") or {}
    # a bare headline value still gates the flagship
    if not cfgs and doc.get("value") is not None:
        cfgs = {"headline": {"tokens_per_sec_chip": doc["value"]}}
    return cfgs


def _metric_of(cfg: dict) -> Optional[Tuple[str, float]]:
    for m in _METRICS:
        if isinstance(cfg.get(m), (int, float)):
            return m, float(cfg[m])
    return None


# plugin spellings of the same accelerator family compare fine
_PLATFORM_FAMILY = {"axon": "tpu"}


def _config_platform(cfg: dict, doc: dict,
                     assumed: Optional[str]) -> Optional[str]:
    """Declared platform of one config: per-config field, else the
    round-level field, else the caller's --assume-baseline-platform."""
    p = cfg.get("platform") if isinstance(cfg, dict) else None
    if not (isinstance(p, str) and p):
        p = doc.get("platform")
    if not (isinstance(p, str) and p):
        p = assumed
    return _PLATFORM_FAMILY.get(p, p) if isinstance(p, str) else None


def _config_scale(cfg: dict) -> str:
    """Declared bench scale of one config; rounds predating the field
    were all full-scale TPU-box runs, so undeclared means "full"."""
    s = cfg.get("scale") if isinstance(cfg, dict) else None
    return s if isinstance(s, str) and s else "full"


def compare(baseline: dict, current: dict, threshold: float,
            baseline_platform: Optional[str] = None):
    """[(config, metric, base, cur, rel_change, status)] — status in
    {"ok", "improved", "regressed", "new", "missing", "incomparable"}.

    A config pair whose two sides DECLARE different platforms (r06+
    records per-config `platform`; older rounds can be stated via
    --assume-baseline-platform, e.g. `tpu` for the r01-r05 driver rounds)
    is "incomparable": a CPU dev-box round vs a TPU round is not a
    regression, and gating on it would either mask real TPU regressions
    or fail every cross-box run. Undeclared-vs-declared still compares
    (best effort), so the gate's behavior on old file pairs is unchanged.
    """
    rows = []
    base_cfgs = _configs(baseline)
    cur_cfgs = _configs(current)
    # round-level platforms identify the BOX: when they are known to
    # differ, every row is incomparable — even an all-CPU config (the
    # wide&deep PS trainer) ran on a different host
    rp_base = _config_platform({}, baseline, baseline_platform)
    rp_cur = _config_platform({}, current, None)
    rounds_differ = bool(rp_base and rp_cur and rp_base != rp_cur)
    for name, bc in base_cfgs.items():
        bm = _metric_of(bc)
        if bm is None:
            continue
        metric, bval = bm
        if bval <= 0:
            # a zero/negative baseline (crashed bench round) can't gate
            # anything — comparing against it would pass any collapse
            rows.append((name, metric, bval, None, None, "missing"))
            continue
        # compare the SAME metric, never a different one the current round
        # happens to also report (units would be incomparable)
        cc = cur_cfgs.get(name) or {}
        cval = cc.get(metric)
        if not isinstance(cval, (int, float)):
            rows.append((name, metric, bval, None, None, "missing"))
            continue
        rel = (cval - bval) / bval
        bp = _config_platform(bc, baseline, baseline_platform)
        cp = _config_platform(cc, current, None)
        # a scale=ci round vs a full-scale baseline (or vice versa) is as
        # incomparable as a different box — the dims/iters differ
        if rounds_differ or (bp and cp and bp != cp) \
                or _config_scale(bc) != _config_scale(cc):
            rows.append((name, metric, bval, float(cval), rel,
                         "incomparable"))
            continue
        status = ("regressed" if rel < -threshold
                  else "improved" if rel > threshold else "ok")
        rows.append((name, metric, bval, float(cval), rel, status))
    for name, cc in cur_cfgs.items():
        if name not in base_cfgs and _metric_of(cc):
            m, v = _metric_of(cc)
            rows.append((name, m, None, v, None, "new"))
    return rows


def _obs_blocks(doc: dict):
    """Yield (where, observability-dict) for the top level and each config."""
    obs = doc.get("observability")
    if isinstance(obs, dict):
        yield "observability", obs
    for name, cfg in (doc.get("configs") or {}).items():
        sub = cfg.get("observability") if isinstance(cfg, dict) else None
        if isinstance(sub, dict):
            yield f"configs.{name}.observability", sub


# the async-checkpoint metric families and the snapshot shape each must
# have when it appears in an observability metrics block
_ASYNC_CKPT_FAMILIES = {
    "checkpoint_async_pending": "gauge",
    "checkpoint_async_bytes": "counter",
    "checkpoint_async_seconds": "histogram",
}


def _validate_async_ckpt_metrics(where: str, metrics: dict) -> List[str]:
    """`checkpoint_async_*` families in a metrics snapshot must be
    well-formed: the right metric kind, numeric non-negative values, and
    (histograms) buckets/sum/count that agree — a bench advertising async
    saves with a garbled hidden-cost histogram fails the gate."""
    problems = []
    for name, fam in metrics.items():
        if not name.startswith("checkpoint_async"):
            continue
        want = _ASYNC_CKPT_FAMILIES.get(name)
        if want is None:
            problems.append(f"{where}.metrics.{name}: unknown "
                            f"checkpoint_async family (expected one of "
                            f"{sorted(_ASYNC_CKPT_FAMILIES)})")
            continue
        if not isinstance(fam, dict) or fam.get("kind") != want:
            problems.append(f"{where}.metrics.{name}: kind "
                            f"{fam.get('kind') if isinstance(fam, dict) else fam!r}"
                            f", expected {want}")
            continue
        values = fam.get("values") or []
        if not isinstance(values, list) or \
                not all(isinstance(v, dict) for v in values):
            problems.append(f"{where}.metrics.{name}.values is not a "
                            f"list of series objects")
            continue
        for i, v in enumerate(values):
            if want == "histogram":
                buckets, cnt = v.get("buckets"), v.get("count")
                if not isinstance(buckets, dict) or \
                        not isinstance(cnt, (int, float)) or \
                        not isinstance(v.get("sum"), (int, float)):
                    problems.append(f"{where}.metrics.{name}[{i}]: "
                                    f"histogram needs buckets/sum/count")
                elif buckets.get("+Inf") != cnt or v["sum"] < 0 or cnt < 0:
                    problems.append(
                        f"{where}.metrics.{name}[{i}]: inconsistent "
                        f"histogram (+Inf bucket {buckets.get('+Inf')} != "
                        f"count {cnt}, or negative sum)")
            else:
                val = v.get("value")
                if not isinstance(val, (int, float)) or val < 0:
                    problems.append(f"{where}.metrics.{name}[{i}]: "
                                    f"value {val!r} is not a non-negative "
                                    f"number")
    return problems


# legal provenance labels for device-time rows: roofline estimate, sync-mode
# wall measurement, or xplane-trace correlation (profiler/xplane.py)
_DEVICE_SRCS = ("estimate", "measured", "xplane")


def _validate_device_time(where: str, dt: dict) -> List[str]:
    """An `observability.device_time` block must be rows of per-op
    host-vs-device aggregates whose `src` (and the block `mode`) is a
    known provenance — a bench claiming measured attribution with a
    garbled or unknown source label fails the gate."""
    problems = []
    if not isinstance(dt, dict):
        return [f"{where}.device_time is not an object"]
    mode = dt.get("mode")
    if mode is not None and mode not in _DEVICE_SRCS:
        problems.append(f"{where}.device_time.mode {mode!r} not in "
                        f"{_DEVICE_SRCS}")
    rows = dt.get("rows")
    if rows is None:
        return problems
    if not isinstance(rows, list):
        return problems + [f"{where}.device_time.rows is not a list"]
    for i, r in enumerate(rows):
        if not isinstance(r, dict):
            problems.append(f"{where}.device_time.rows[{i}] is not an "
                            f"object")
            continue
        if not isinstance(r.get("op"), str) or not r.get("op"):
            problems.append(f"{where}.device_time.rows[{i}].op "
                            f"{r.get('op')!r} is not a non-empty string")
        for key in ("calls", "host_ms", "device_ms"):
            v = r.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                problems.append(f"{where}.device_time.rows[{i}].{key} "
                                f"{v!r} is not a non-negative number")
        if r.get("src") not in _DEVICE_SRCS:
            problems.append(f"{where}.device_time.rows[{i}].src "
                            f"{r.get('src')!r} not in {_DEVICE_SRCS}")
    return problems


# training-health + AMP metric families: name -> (kind, required labels,
# non-negative values?). Gauges that can legally go negative (a loss) skip
# the non-negative check; counters never may.
_HEALTH_FAMILIES = {
    "health_loss": ("gauge", (), False),
    "health_grad_norm": ("gauge", (), True),
    "health_update_ratio": ("gauge", (), True),
    "health_layer_grad_norm": ("gauge", ("group",), True),
    "health_nonfinite_total": ("counter", ("src",), True),
    "health_alerts_total": ("counter", ("signal",), True),
    "health_rollback_total": ("counter", (), True),
    "fleet_health_status": ("gauge", ("host",), True),
    "amp_found_inf_total": ("counter", (), True),
    "amp_loss_scale": ("gauge", (), True),
}


def _validate_health_metrics(where: str, metrics: dict) -> List[str]:
    """`health_*` / `amp_*` families must be the documented kind, carry
    their required labels, and hold finite values (counters and norms
    non-negative) — label hygiene for the numerics plane."""
    problems = []
    for name, fam in metrics.items():
        if not (name.startswith("health_") or name.startswith("amp_")
                or name == "fleet_health_status"):
            continue
        spec = _HEALTH_FAMILIES.get(name)
        if spec is None:
            problems.append(f"{where}.metrics.{name}: unknown health/amp "
                            f"family (expected one of "
                            f"{sorted(_HEALTH_FAMILIES)})")
            continue
        kind, req_labels, nonneg = spec
        if not isinstance(fam, dict) or fam.get("kind") != kind:
            problems.append(f"{where}.metrics.{name}: kind "
                            f"{fam.get('kind') if isinstance(fam, dict) else fam!r}"
                            f", expected {kind}")
            continue
        for i, v in enumerate(fam.get("values") or []):
            if not isinstance(v, dict):
                problems.append(f"{where}.metrics.{name}[{i}] is not a "
                                f"series object")
                continue
            val = v.get("value")
            if not isinstance(val, (int, float)) or isinstance(val, bool):
                problems.append(f"{where}.metrics.{name}[{i}]: value "
                                f"{val!r} is not a number")
            elif val != val or val in (float("inf"), float("-inf")):
                problems.append(f"{where}.metrics.{name}[{i}]: value "
                                f"{val!r} is not finite (the plane must "
                                f"keep NaN/Inf out of gauges)")
            elif nonneg and val < 0:
                problems.append(f"{where}.metrics.{name}[{i}]: value "
                                f"{val!r} is negative")
            labels = v.get("labels") or {}
            for lk in req_labels:
                if lk not in labels:
                    problems.append(f"{where}.metrics.{name}[{i}]: series "
                                    f"missing the {lk!r} label")
    return problems


def _validate_health_block(where: str, h: dict) -> List[str]:
    """The bench `observability.health` block: the sentinel-overhead
    measurement (health on vs off on the GPT-2 config) plus the last
    decoded sentinel stats."""
    problems = []
    if not isinstance(h, dict):
        return [f"{where}.health is not an object"]
    if "error" in h:
        return problems  # a failed probe reports itself; nothing to gate
    for key in ("step_ms_off", "step_ms_on"):
        v = h.get(key)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{where}.health.{key} {v!r} is not a "
                            f"non-negative number")
    ov = h.get("overhead_frac")
    if ov is not None and (not isinstance(ov, (int, float))
                           or isinstance(ov, bool) or ov < -1.0):
        problems.append(f"{where}.health.overhead_frac {ov!r} is not a "
                        f"number > -1")
    for key in ("interval", "groups"):
        v = h.get(key)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{where}.health.{key} {v!r} is not a "
                            f"non-negative integer")
    sent = h.get("sentinel")
    if sent is not None:
        if not isinstance(sent, dict):
            problems.append(f"{where}.health.sentinel is not an object")
        else:
            nf = sent.get("nonfinite")
            if nf is not None and not isinstance(nf, bool):
                problems.append(f"{where}.health.sentinel.nonfinite "
                                f"{nf!r} is not a bool")
            for key in ("loss", "grad_norm", "update_ratio"):
                v = sent.get(key)
                if v is not None and (not isinstance(v, (int, float))
                                      or isinstance(v, bool)):
                    problems.append(f"{where}.health.sentinel.{key} "
                                    f"{v!r} is not numeric or null")
    return problems


# kernel-autotuner metric families: name -> (kind, required labels).
# Every value must be a finite non-negative number (probe seconds,
# event/tune counts, chosen-config probe-ms gauges — none may go negative).
_AUTOTUNE_FAMILIES = {
    "autotune_cache_events_total": ("counter", ("event", "op")),
    "autotune_tunes_total": ("counter", ("op",)),
    "autotune_probe_seconds": ("histogram", ("op",)),
    "autotune_chosen_config": ("gauge", ("op", "config")),
}


def _validate_autotune_metrics(where: str, metrics: dict) -> List[str]:
    """`autotune_*` families must be the documented kind, carry their
    required labels, and hold non-negative values (histograms: consistent
    buckets/sum/count) — the autotuner's observability contract."""
    problems = []
    for name, fam in metrics.items():
        if not name.startswith("autotune_"):
            continue
        spec = _AUTOTUNE_FAMILIES.get(name)
        if spec is None:
            problems.append(f"{where}.metrics.{name}: unknown autotune "
                            f"family (expected one of "
                            f"{sorted(_AUTOTUNE_FAMILIES)})")
            continue
        kind, req_labels = spec
        if not isinstance(fam, dict) or fam.get("kind") != kind:
            problems.append(f"{where}.metrics.{name}: kind "
                            f"{fam.get('kind') if isinstance(fam, dict) else fam!r}"
                            f", expected {kind}")
            continue
        values = fam.get("values") or []
        if not isinstance(values, list):
            problems.append(f"{where}.metrics.{name}.values is not a list")
            continue
        for i, v in enumerate(values):
            if not isinstance(v, dict):
                problems.append(f"{where}.metrics.{name}[{i}] is not a "
                                f"series object")
                continue
            if kind == "histogram":
                buckets, cnt = v.get("buckets"), v.get("count")
                if not isinstance(buckets, dict) or \
                        not isinstance(cnt, (int, float)) or \
                        not isinstance(v.get("sum"), (int, float)):
                    problems.append(f"{where}.metrics.{name}[{i}]: "
                                    f"histogram needs buckets/sum/count")
                elif buckets.get("+Inf") != cnt or v["sum"] < 0 or cnt < 0:
                    problems.append(
                        f"{where}.metrics.{name}[{i}]: inconsistent "
                        f"histogram (+Inf bucket {buckets.get('+Inf')} != "
                        f"count {cnt}, or negative sum)")
            else:
                val = v.get("value")
                if not isinstance(val, (int, float)) or \
                        isinstance(val, bool) or val != val or val < 0:
                    problems.append(f"{where}.metrics.{name}[{i}]: value "
                                    f"{val!r} is not a non-negative number")
            labels = v.get("labels") or {}
            for lk in req_labels:
                if lk not in labels:
                    problems.append(f"{where}.metrics.{name}[{i}]: series "
                                    f"missing the {lk!r} label")
    return problems


# continuous-batching serving metric families: name -> (kind, required
# labels). All values non-negative. The latency histograms additionally
# carry a `path` label since serving v2 (fused|eager decode) — optional
# here so pre-v2 bench artifacts stay valid, but when present the value
# must be one of _SERVING_PATHS.
_SERVING_FAMILIES = {
    "serving_queue_depth": ("gauge", ("model",)),
    "serving_batch_occupancy": ("gauge", ("model",)),
    "serving_ttft_seconds": ("histogram", ("model",)),
    "serving_tpot_seconds": ("histogram", ("model",)),
    "serving_goodput_tokens_total": ("counter", ("model",)),
    # request-scoped phase histograms (profiler/reqtrace.py)
    "serving_queue_wait_seconds": ("histogram", ("model",)),
    "serving_prefill_seconds": ("histogram", ("model",)),
    "serving_preempt_requeue_seconds": ("histogram", ("model",)),
    # self-healing plane (inference/hotswap.py + the engine watchdog)
    "serving_swap_total": ("counter", ("model", "outcome")),
    "serving_swap_pause_seconds": ("histogram", ("model",)),
    "serving_swap_step": ("gauge", ("model",)),
    "serving_restart_total": ("counter", ("model", "reason")),
    "serving_suspended": ("gauge", ("model",)),
    # disaggregated prefill/decode handoff plane (inference/disagg.py)
    "serving_handoff_depth": ("gauge", ("model",)),
    "serving_handoff_wait_seconds": ("histogram", ("model",)),
    "serving_handoff_bytes_total": ("counter", ("model",)),
    "serving_stage_occupancy": ("gauge", ("model", "stage")),
}

#: legal `stage` label values on serving_stage_occupancy (the two-stage
#: disaggregated pipeline)
_STAGES = ("prefill", "decode")

#: families whose gauge value may legitimately be negative
#: (serving_swap_step is -1 until a hot-swap lands)
_SERVING_SIGNED = ("serving_swap_step",)

#: legal `outcome` label values on serving_swap_total
_SWAP_OUTCOMES = ("applied", "rolled_back", "rejected", "failed")

# serving SLO-plane families (profiler/slo.py): breach excursions and
# the live window p99 per signal
_SLO_FAMILIES = {
    "slo_breaches_total": ("counter", ("model", "signal")),
    "slo_breached": ("gauge", ("model", "signal")),
    "slo_window_p99_seconds": ("gauge", ("model", "signal")),
}

#: legal decode-path label values on the serving latency histograms
_SERVING_PATHS = ("fused", "eager")


def _validate_serving_metrics(where: str, metrics: dict) -> List[str]:
    """`serving_*` families must be the documented kind, carry the
    `model` label, and hold non-negative values (histograms: consistent
    buckets/sum/count) — the serving plane's observability contract."""
    problems = []
    for name, fam in metrics.items():
        if not name.startswith("serving_"):
            continue
        spec = _SERVING_FAMILIES.get(name)
        if spec is None:
            problems.append(f"{where}.metrics.{name}: unknown serving "
                            f"family (expected one of "
                            f"{sorted(_SERVING_FAMILIES)})")
            continue
        kind, req_labels = spec
        if not isinstance(fam, dict) or fam.get("kind") != kind:
            problems.append(
                f"{where}.metrics.{name}: kind "
                f"{fam.get('kind') if isinstance(fam, dict) else fam!r}"
                f", expected {kind}")
            continue
        for i, v in enumerate(fam.get("values") or []):
            if not isinstance(v, dict):
                problems.append(f"{where}.metrics.{name}[{i}] is not a "
                                f"series object")
                continue
            if kind == "histogram":
                buckets, cnt = v.get("buckets"), v.get("count")
                if not isinstance(buckets, dict) or \
                        not isinstance(cnt, (int, float)) or \
                        not isinstance(v.get("sum"), (int, float)):
                    problems.append(f"{where}.metrics.{name}[{i}]: "
                                    f"histogram needs buckets/sum/count")
                elif buckets.get("+Inf") != cnt or v["sum"] < 0 or cnt < 0:
                    problems.append(
                        f"{where}.metrics.{name}[{i}]: inconsistent "
                        f"histogram (+Inf bucket {buckets.get('+Inf')} != "
                        f"count {cnt}, or negative sum)")
            else:
                val = v.get("value")
                if not isinstance(val, (int, float)) or \
                        isinstance(val, bool) or val != val or \
                        (val < 0 and name not in _SERVING_SIGNED):
                    problems.append(f"{where}.metrics.{name}[{i}]: value "
                                    f"{val!r} is not a non-negative number")
            labels = v.get("labels") or {}
            for lk in req_labels:
                if lk not in labels:
                    problems.append(f"{where}.metrics.{name}[{i}]: series "
                                    f"missing the {lk!r} label")
            path = labels.get("path")
            if path is not None and path not in _SERVING_PATHS:
                problems.append(f"{where}.metrics.{name}[{i}]: path label "
                                f"{path!r} is not one of {_SERVING_PATHS}")
            if name == "serving_swap_total" and \
                    labels.get("outcome") not in _SWAP_OUTCOMES:
                problems.append(
                    f"{where}.metrics.{name}[{i}]: outcome label "
                    f"{labels.get('outcome')!r} is not one of "
                    f"{_SWAP_OUTCOMES}")
            if name == "serving_stage_occupancy" and \
                    labels.get("stage") not in _STAGES:
                problems.append(
                    f"{where}.metrics.{name}[{i}]: stage label "
                    f"{labels.get('stage')!r} is not one of {_STAGES}")
    return problems


def _validate_slo_metrics(where: str, metrics: dict) -> List[str]:
    """`slo_*` families must be the documented kind and carry the
    model+signal labels; an unknown `slo_*` family is NAMED (a typo'd
    breach counter silently passing is exactly what this gate exists to
    catch)."""
    problems = []
    for name, fam in metrics.items():
        if not name.startswith("slo_"):
            continue
        spec = _SLO_FAMILIES.get(name)
        if spec is None:
            problems.append(f"{where}.metrics.{name}: unknown slo family "
                            f"(expected one of {sorted(_SLO_FAMILIES)})")
            continue
        kind, req_labels = spec
        if not isinstance(fam, dict) or fam.get("kind") != kind:
            problems.append(
                f"{where}.metrics.{name}: kind "
                f"{fam.get('kind') if isinstance(fam, dict) else fam!r}"
                f", expected {kind}")
            continue
        for i, v in enumerate(fam.get("values") or []):
            if not isinstance(v, dict):
                problems.append(f"{where}.metrics.{name}[{i}] is not a "
                                f"series object")
                continue
            if not _nonneg_num(v.get("value")):
                problems.append(f"{where}.metrics.{name}[{i}]: value "
                                f"{v.get('value')!r} is not a "
                                f"non-negative number")
            labels = v.get("labels") or {}
            for lk in req_labels:
                if lk not in labels:
                    problems.append(f"{where}.metrics.{name}[{i}]: series "
                                    f"missing the {lk!r} label")
    return problems


def _finite_nonneg(v) -> bool:
    return _nonneg_num(v) and v != float("inf")


_TRACE_PHASES = ("queued", "prefill", "decode", "preempted", "complete",
                 "failed")


def _validate_trace(where: str, t: dict) -> List[str]:
    """One request-trace record: ids, non-negative per-phase durations
    over the known phase names, spans with end >= start."""
    problems = []
    if not isinstance(t, dict):
        return [f"{where} is not a trace object"]
    for key in ("trace_id", "rid"):
        v = t.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            problems.append(f"{where}.{key}: {v!r} is not a positive id")
    for key in ("preemptions", "decode_iterations", "decode_tokens"):
        if key in t and not _nonneg_num(t.get(key)):
            problems.append(f"{where}.{key}: {t.get(key)!r} is not a "
                            f"non-negative number")
    e2e = t.get("e2e_s")
    if e2e is not None and not _finite_nonneg(e2e):
        problems.append(f"{where}.e2e_s: {e2e!r} is not finite "
                        f"non-negative")
    phases = t.get("phases")
    if phases is not None:
        if not isinstance(phases, dict):
            problems.append(f"{where}.phases is not an object")
        else:
            for ph, dur in phases.items():
                if ph not in _TRACE_PHASES:
                    problems.append(f"{where}.phases.{ph}: unknown phase "
                                    f"(expected one of {_TRACE_PHASES})")
                if not _finite_nonneg(dur):
                    problems.append(f"{where}.phases.{ph}: duration "
                                    f"{dur!r} is not finite non-negative")
    for i, s in enumerate(t.get("spans") or []):
        if not isinstance(s, dict) or s.get("phase") not in _TRACE_PHASES:
            problems.append(f"{where}.spans[{i}]: bad span/phase")
            continue
        start, end = s.get("start"), s.get("end")
        if end is not None and isinstance(start, (int, float)) \
                and isinstance(end, (int, float)) and end < start:
            problems.append(f"{where}.spans[{i}]: end {end} < start "
                            f"{start}")
    return problems


def _validate_reqtrace_block(where: str, rt: dict) -> List[str]:
    """The bench `observability.reqtrace` block / `/requests` payload:
    live + completed trace lists, each conforming to the trace shape."""
    if not isinstance(rt, dict):
        return [f"{where} is not an object"]
    if "error" in rt:
        return []  # a failed probe reports itself
    problems = []
    for key in ("live", "completed"):
        lst = rt.get(key)
        if lst is None:
            continue
        if not isinstance(lst, list):
            problems.append(f"{where}.{key} is not a list")
            continue
        for i, t in enumerate(lst):
            problems.extend(_validate_trace(f"{where}.{key}[{i}]", t))
    return problems


def _validate_slo_block(where: str, s: dict) -> List[str]:
    """The bench `observability.slo` block / `/slo` payload: per-signal
    window quantiles finite and monotone (p50 <= p95 <= p99), breach
    counts non-negative."""
    if not isinstance(s, dict):
        return [f"{where} is not an object"]
    if "error" in s:
        return []  # a failed probe reports itself
    problems = []
    targets = s.get("targets")
    if targets is not None and not isinstance(targets, dict):
        problems.append(f"{where}.targets is not an object")
    elif targets:
        for sig, t in targets.items():
            if not _finite_nonneg(t):
                problems.append(f"{where}.targets.{sig}: {t!r} is not "
                                f"finite non-negative")
    signals = s.get("signals")
    if signals is not None:
        if not isinstance(signals, dict):
            problems.append(f"{where}.signals is not an object")
        else:
            for sig, qs in signals.items():
                w = f"{where}.signals.{sig}"
                if not isinstance(qs, dict):
                    problems.append(f"{w} is not an object")
                    continue
                if not _nonneg_num(qs.get("count")):
                    problems.append(f"{w}.count: {qs.get('count')!r} is "
                                    f"not a non-negative number")
                vals = [qs.get(q) for q in ("p50", "p95", "p99")]
                if any(v is not None for v in vals):
                    if not all(_finite_nonneg(v) for v in vals):
                        problems.append(f"{w}: quantiles {vals!r} must "
                                        f"all be finite non-negative")
                    elif not (vals[0] <= vals[1] <= vals[2]):
                        problems.append(f"{w}: quantiles not monotone "
                                        f"(p50 {vals[0]} <= p95 {vals[1]} "
                                        f"<= p99 {vals[2]} violated)")
    stats = s.get("stats")
    if stats is not None:
        if not isinstance(stats, dict):
            problems.append(f"{where}.stats is not an object")
        else:
            for key in ("breaches", "recoveries", "observations"):
                if key in stats and not _nonneg_num(stats.get(key)):
                    problems.append(f"{where}.stats.{key}: "
                                    f"{stats.get(key)!r} is not a "
                                    f"non-negative count")
    breached = s.get("breached")
    if breached is not None and not isinstance(breached, dict):
        problems.append(f"{where}.breached is not an object")
    return problems


def _validate_decode_block(where: str, cfg: dict) -> List[str]:
    """The `gpt2_decode` bench config: serving percentiles (TTFT/TPOT),
    goodput fields, and the paged-vs-dense A/B rows — a decode round
    claiming super-linear speedup with malformed numbers fails the
    gate like a perf regression does."""
    problems = []
    srv = cfg.get("serving")
    if srv is not None:
        if not isinstance(srv, dict):
            problems.append(f"{where}.serving is not an object")
        else:
            for fam in ("ttft_s", "tpot_s"):
                blk = srv.get(fam)
                if blk is None:
                    problems.append(f"{where}.serving.{fam} is missing")
                    continue
                if not isinstance(blk, dict):
                    problems.append(f"{where}.serving.{fam} is not an "
                                    f"object")
                    continue
                for q in ("p50", "p99"):
                    v = blk.get(q)
                    if v is not None and not _nonneg_num(v):
                        problems.append(f"{where}.serving.{fam}.{q} {v!r} "
                                        f"is not a non-negative number or "
                                        f"null")
            qw = srv.get("queue_wait_s")  # optional (added with reqtrace)
            if qw is not None:
                if not isinstance(qw, dict):
                    problems.append(f"{where}.serving.queue_wait_s is "
                                    f"not an object")
                else:
                    for q in ("p50", "p99"):
                        v = qw.get(q)
                        if v is not None and not _nonneg_num(v):
                            problems.append(
                                f"{where}.serving.queue_wait_s.{q} {v!r} "
                                f"is not a non-negative number or null")
            ws = srv.get("wall_s")
            if ws is not None and not _nonneg_num(ws):
                problems.append(f"{where}.serving.wall_s {ws!r} is not a "
                                f"non-negative number")
    for key in ("goodput_tokens", "streams", "completed", "preemptions"):
        v = cfg.get(key)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            problems.append(f"{where}.{key} {v!r} is not a non-negative "
                            f"integer")
    for key in ("tokens_per_sec_chip", "decode_tokens_per_sec",
                "batch_occupancy_mean"):
        v = cfg.get(key)
        if v is not None and not _nonneg_num(v):
            problems.append(f"{where}.{key} {v!r} is not a non-negative "
                            f"number or null")
    ab = cfg.get("paged_vs_dense")
    if ab is not None:
        if not isinstance(ab, dict):
            problems.append(f"{where}.paged_vs_dense is not an object")
        elif "error" not in ab:  # a failed probe reports itself
            rows = ab.get("rows")
            if not isinstance(rows, list) or not rows:
                problems.append(f"{where}.paged_vs_dense.rows is not a "
                                f"non-empty list")
            else:
                for i, r in enumerate(rows):
                    if not isinstance(r, dict):
                        problems.append(
                            f"{where}.paged_vs_dense.rows[{i}] is not an "
                            f"object")
                        continue
                    c = r.get("ctx")
                    if not isinstance(c, int) or isinstance(c, bool) \
                            or c <= 0:
                        problems.append(
                            f"{where}.paged_vs_dense.rows[{i}].ctx {c!r} "
                            f"is not a positive integer")
                    for key in ("paged_ms_per_token",
                                "dense_ms_per_token"):
                        if not _nonneg_num(r.get(key)):
                            problems.append(
                                f"{where}.paged_vs_dense.rows[{i}].{key} "
                                f"{r.get(key)!r} is not a non-negative "
                                f"number")
            for key in ("paged_growth", "dense_growth",
                        "speedup_at_max_ctx"):
                v = ab.get(key)
                if v is not None and not _nonneg_num(v):
                    problems.append(f"{where}.paged_vs_dense.{key} {v!r} "
                                    f"is not a non-negative number or null")
    fve = cfg.get("fused_vs_eager")
    if fve is not None:
        if not isinstance(fve, dict):
            problems.append(f"{where}.fused_vs_eager is not an object")
        elif "error" not in fve:  # a failed probe reports itself
            for key in ("fused_ms_per_token", "eager_ms_per_token"):
                if not _nonneg_num(fve.get(key)):
                    problems.append(f"{where}.fused_vs_eager.{key} "
                                    f"{fve.get(key)!r} is not a "
                                    f"non-negative number")
            sp = fve.get("speedup")
            if sp is not None and not _nonneg_num(sp):
                problems.append(f"{where}.fused_vs_eager.speedup {sp!r} "
                                f"is not a non-negative number or null")
            # the bit-parity claim: both decode paths MUST emit the same
            # tokens — a fused path that drifts is a correctness bug the
            # gate treats like a regression
            if fve.get("identical_tokens") is not True:
                problems.append(f"{where}.fused_vs_eager.identical_tokens "
                                f"{fve.get('identical_tokens')!r}: fused "
                                f"and eager decode disagreed on tokens")
    shp = cfg.get("shared_prefix")
    if shp is not None:
        if not isinstance(shp, dict):
            problems.append(f"{where}.shared_prefix is not an object")
        elif "error" not in shp:
            for side in ("on", "off"):
                blk = shp.get(side)
                if not isinstance(blk, dict):
                    problems.append(f"{where}.shared_prefix.{side} is not "
                                    f"an object")
                    continue
                for key in ("min_free_pages", "prefix_hit_tokens",
                            "shared_admissions", "cow_copies",
                            "preemptions", "completed", "leaked_pages"):
                    v = blk.get(key)
                    if not isinstance(v, int) or isinstance(v, bool) \
                            or v < 0:
                        problems.append(
                            f"{where}.shared_prefix.{side}.{key} {v!r} is "
                            f"not a non-negative integer")
                # a leaked page means a refcount failed to return to zero
                if blk.get("leaked_pages") not in (None, 0):
                    problems.append(
                        f"{where}.shared_prefix.{side}.leaked_pages "
                        f"{blk.get('leaked_pages')!r}: allocator held "
                        f"pages after all requests finished")
            off = shp.get("off")
            if isinstance(off, dict) and off.get("prefix_hit_tokens"):
                problems.append(
                    f"{where}.shared_prefix.off.prefix_hit_tokens "
                    f"{off.get('prefix_hit_tokens')!r}: sharing disabled "
                    f"but prefix hits were recorded")
    tpd = cfg.get("tp_decode")
    if tpd is not None:
        if not isinstance(tpd, dict):
            problems.append(f"{where}.tp_decode is not an object")
        elif "error" not in tpd and "skipped" not in tpd:
            for key in ("single_ms_per_token", "tp_ms_per_token"):
                if not _nonneg_num(tpd.get(key)):
                    problems.append(f"{where}.tp_decode.{key} "
                                    f"{tpd.get(key)!r} is not a "
                                    f"non-negative number")
            deg = tpd.get("tp_degree")
            if not isinstance(deg, int) or isinstance(deg, bool) \
                    or deg < 2:
                problems.append(f"{where}.tp_decode.tp_degree {deg!r} is "
                                f"not an integer >= 2")
            ratio = tpd.get("tpot_ratio")
            if ratio is not None and not _nonneg_num(ratio):
                problems.append(f"{where}.tp_decode.tpot_ratio {ratio!r} "
                                f"is not a non-negative number or null")
            # the bit-parity claim: head-sharding is a LAYOUT change —
            # TP tokens drifting from single-chip is a correctness bug
            if tpd.get("identical_tokens") is not True:
                problems.append(f"{where}.tp_decode.identical_tokens "
                                f"{tpd.get('identical_tokens')!r}: TP and "
                                f"single-chip decode disagreed on tokens")
            link = tpd.get("collective_bytes_by_link")
            if isinstance(link, dict) and "error" not in link:
                for lk in ("ici", "dcn"):
                    if not _nonneg_num(link.get(lk)):
                        problems.append(
                            f"{where}.tp_decode.collective_bytes_by_link"
                            f".{lk} {link.get(lk)!r} is not a "
                            f"non-negative number")
    dis = cfg.get("disagg")
    if dis is not None:
        if not isinstance(dis, dict):
            problems.append(f"{where}.disagg is not an object")
        elif "error" not in dis and "skipped" not in dis:
            for key in ("colocated_ms_per_token", "disagg_ms_per_token"):
                if not _nonneg_num(dis.get(key)):
                    problems.append(f"{where}.disagg.{key} "
                                    f"{dis.get(key)!r} is not a "
                                    f"non-negative number")
            for key in ("handoffs", "prefill_workers"):
                v = dis.get(key)
                if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                    problems.append(f"{where}.disagg.{key} {v!r} is not a "
                                    f"positive integer")
            # the disaggregation claim itself: EVERY prefill ran on a
            # prefill worker — a nonzero decode-side prefill count means
            # the stages were never actually split
            if dis.get("decode_prefills") != 0:
                problems.append(f"{where}.disagg.decode_prefills "
                                f"{dis.get('decode_prefills')!r}: the "
                                f"decode engine ran prefills itself")
            if dis.get("identical_tokens") is not True:
                problems.append(f"{where}.disagg.identical_tokens "
                                f"{dis.get('identical_tokens')!r}: "
                                f"disagg and co-located decode disagreed "
                                f"on tokens")
    return problems


# fleet-controller metric families: name -> (kind, required labels).
_CONTROLLER_FAMILIES = {
    "controller_decisions_total": ("counter", ("policy", "outcome")),
    "controller_evictions_total": ("counter", ("host",)),
    "controller_rollbacks_total": ("counter", ("host",)),
    "controller_readmissions_total": ("counter", ("host",)),
    "controller_relaunch_to_first_step_seconds": ("gauge", ("policy",)),
    # HA control plane: election term, takeovers, fenced stale actuations
    "controller_leader_term": ("gauge", ()),
    "controller_takeovers_total": ("counter", ("reason",)),
    "controller_fenced_total": ("counter", ("policy",)),
}

#: legal controller_decision outcomes (the decision contract);
#: `fenced` = the actuation carried a stale leadership term and was
#: rejected at the actuation boundary
_CONTROLLER_OUTCOMES = ("applied", "dry_run", "failed", "fenced")


def _validate_controller_metrics(where: str, metrics: dict) -> List[str]:
    """`controller_*` families must be the documented kind, carry their
    required labels, and hold non-negative values — the self-driving
    fleet's observability contract."""
    problems = []
    for name, fam in metrics.items():
        if not name.startswith("controller_"):
            continue
        spec = _CONTROLLER_FAMILIES.get(name)
        if spec is None:
            problems.append(f"{where}.metrics.{name}: unknown controller "
                            f"family (expected one of "
                            f"{sorted(_CONTROLLER_FAMILIES)})")
            continue
        kind, req_labels = spec
        if not isinstance(fam, dict) or fam.get("kind") != kind:
            problems.append(
                f"{where}.metrics.{name}: kind "
                f"{fam.get('kind') if isinstance(fam, dict) else fam!r}"
                f", expected {kind}")
            continue
        for i, v in enumerate(fam.get("values") or []):
            if not isinstance(v, dict):
                problems.append(f"{where}.metrics.{name}[{i}] is not a "
                                f"series object")
                continue
            val = v.get("value")
            if not isinstance(val, (int, float)) or isinstance(val, bool) \
                    or val != val or val < 0:
                problems.append(f"{where}.metrics.{name}[{i}]: value "
                                f"{val!r} is not a non-negative number")
            labels = v.get("labels") or {}
            for lk in req_labels:
                if lk not in labels:
                    problems.append(f"{where}.metrics.{name}[{i}]: series "
                                    f"missing the {lk!r} label")
            if name == "controller_decisions_total" \
                    and labels.get("outcome") not in _CONTROLLER_OUTCOMES:
                problems.append(
                    f"{where}.metrics.{name}[{i}]: outcome "
                    f"{labels.get('outcome')!r} not in "
                    f"{_CONTROLLER_OUTCOMES}")
    return problems


# disaggregated-serving fault-tolerance families: name -> (kind,
# required labels)
_DISAGG_FAMILIES = {
    "disagg_worker_restarts_total": ("counter", ()),
    "disagg_requeue_total": ("counter", ("reason",)),
}


def _validate_disagg_metrics(where: str, metrics: dict) -> List[str]:
    """`disagg_*` families must be the documented kind, carry their
    required labels, and hold non-negative values — the disaggregated
    pipeline's fault-tolerance observability contract."""
    problems = []
    for name, fam in metrics.items():
        if not name.startswith("disagg_"):
            continue
        spec = _DISAGG_FAMILIES.get(name)
        if spec is None:
            problems.append(f"{where}.metrics.{name}: unknown disagg "
                            f"family (expected one of "
                            f"{sorted(_DISAGG_FAMILIES)})")
            continue
        kind, req_labels = spec
        if not isinstance(fam, dict) or fam.get("kind") != kind:
            problems.append(
                f"{where}.metrics.{name}: kind "
                f"{fam.get('kind') if isinstance(fam, dict) else fam!r}"
                f", expected {kind}")
            continue
        for i, v in enumerate(fam.get("values") or []):
            if not isinstance(v, dict):
                problems.append(f"{where}.metrics.{name}[{i}] is not a "
                                f"series object")
                continue
            val = v.get("value")
            if not isinstance(val, (int, float)) or isinstance(val, bool) \
                    or val != val or val < 0:
                problems.append(f"{where}.metrics.{name}[{i}]: value "
                                f"{val!r} is not a non-negative number")
            labels = v.get("labels") or {}
            for lk in req_labels:
                if lk not in labels:
                    problems.append(f"{where}.metrics.{name}[{i}]: series "
                                    f"missing the {lk!r} label")
    return problems


def _validate_controller_decision(where: str, ev: dict) -> List[str]:
    """Beyond the generic event schema, a `controller_decision` event
    must carry the decision contract: policy, action, a legal outcome,
    and a decision id — the fields operators and tooling key on."""
    problems = []
    if not isinstance(ev.get("policy"), str) or not ev.get("policy"):
        problems.append(f"{where}: 'policy' must be a non-empty string, "
                        f"got {ev.get('policy')!r}")
    if not isinstance(ev.get("action"), str) or not ev.get("action"):
        problems.append(f"{where}: 'action' must be a non-empty string, "
                        f"got {ev.get('action')!r}")
    if ev.get("outcome") not in _CONTROLLER_OUTCOMES:
        problems.append(f"{where}: 'outcome' {ev.get('outcome')!r} not in "
                        f"{_CONTROLLER_OUTCOMES}")
    dec = ev.get("decision")
    if not isinstance(dec, int) or isinstance(dec, bool) or dec < 1:
        problems.append(f"{where}: 'decision' must be a positive integer "
                        f"id, got {dec!r}")
    if "evidence" in ev and not isinstance(ev["evidence"], dict):
        problems.append(f"{where}: 'evidence' must be an object, got "
                        f"{type(ev['evidence']).__name__}")
    return problems


def _validate_autotune_block(where: str, at: dict) -> List[str]:
    """A bench `autotune` block (per config, and the summary under
    `observability.autotune`): enabled flag, event-count deltas, and the
    tuned/disk-hit log — each tuned entry names its op and config and
    carries a non-negative (or null) probe_ms."""
    problems = []
    if not isinstance(at, dict):
        return [f"{where} is not an object"]
    if "enabled" in at and not isinstance(at["enabled"], bool):
        problems.append(f"{where}.enabled {at['enabled']!r} is not a bool")
    mode = at.get("mode")
    if mode is not None and mode not in ("off", "on", "force"):
        problems.append(f"{where}.mode {mode!r} not in (off, on, force)")
    cd = at.get("cache_dir")
    if cd is not None and not isinstance(cd, str):
        problems.append(f"{where}.cache_dir {cd!r} is not a string or null")
    events = at.get("events")
    if events is not None:
        if not isinstance(events, dict):
            problems.append(f"{where}.events is not an object")
        else:
            for ev, n in events.items():
                if not isinstance(n, (int, float)) or isinstance(n, bool) \
                        or n != n or n < 0:
                    problems.append(f"{where}.events[{ev!r}] {n!r} is not "
                                    f"a non-negative number")
    tuned = at.get("tuned")
    if tuned is not None:
        if not isinstance(tuned, list):
            problems.append(f"{where}.tuned is not a list")
        else:
            for i, t in enumerate(tuned):
                if not isinstance(t, dict):
                    problems.append(f"{where}.tuned[{i}] is not an object")
                    continue
                if not isinstance(t.get("op"), str) or not t.get("op"):
                    problems.append(f"{where}.tuned[{i}].op {t.get('op')!r} "
                                    f"is not a non-empty string")
                if not isinstance(t.get("config"), (str, dict)):
                    problems.append(f"{where}.tuned[{i}].config "
                                    f"{t.get('config')!r} is not a string "
                                    f"or object")
                pm = t.get("probe_ms")
                if pm is not None and (not isinstance(pm, (int, float))
                                       or isinstance(pm, bool)
                                       or pm != pm or pm < 0):
                    problems.append(f"{where}.tuned[{i}].probe_ms {pm!r} "
                                    f"is not a non-negative number or null")
    return problems


def _nonneg_num(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and v == v and v >= 0)


def _validate_segments(where: str, seg: dict) -> List[str]:
    """A `profile.segments` block (measured per-segment device-time
    attribution from profiler/xplane.segment_breakdown): every segment
    row needs non-negative device_ms / events and a frac in [0, 1] (or
    null on an empty trace); attributed_frac likewise. A bench claiming
    measured segment attribution with malformed rows fails the gate."""
    problems = []
    if not isinstance(seg, dict):
        return [f"{where} is not an object"]
    rows = seg.get("segments")
    if rows is None or not isinstance(rows, dict):
        return [f"{where}.segments is not an object"]
    for name, r in rows.items():
        if not isinstance(r, dict):
            problems.append(f"{where}.segments[{name!r}] is not an object")
            continue
        if not _nonneg_num(r.get("device_ms")):
            problems.append(f"{where}.segments[{name!r}].device_ms "
                            f"{r.get('device_ms')!r} is not a non-negative "
                            f"number")
        ev = r.get("events")
        if not isinstance(ev, int) or isinstance(ev, bool) or ev < 0:
            problems.append(f"{where}.segments[{name!r}].events {ev!r} is "
                            f"not a non-negative integer")
        fr = r.get("frac")
        if fr is not None and (not _nonneg_num(fr) or fr > 1.0 + 1e-9):
            problems.append(f"{where}.segments[{name!r}].frac {fr!r} is "
                            f"not in [0, 1] or null")
    if not _nonneg_num(seg.get("total_device_ms")):
        problems.append(f"{where}.total_device_ms "
                        f"{seg.get('total_device_ms')!r} is not a "
                        f"non-negative number")
    af = seg.get("attributed_frac")
    if af is not None and (not _nonneg_num(af) or af > 1.0 + 1e-9):
        problems.append(f"{where}.attributed_frac {af!r} is not in "
                        f"[0, 1] or null")
    return problems


def _validate_conv_fusion(where: str, cf: dict) -> List[str]:
    """A resnet `conv_fusion` A/B probe block: on/off probe times and
    cost-analysis HBM bytes must be non-negative numbers (or null), the
    engagement flags bools, and kernel_stats non-negative counters."""
    problems = []
    if not isinstance(cf, dict):
        return [f"{where} is not an object"]
    if "error" in cf:
        return problems  # a failed probe reports itself; nothing to gate
    for key in ("enabled", "engaged"):
        v = cf.get(key)
        if v is not None and not isinstance(v, bool):
            problems.append(f"{where}.{key} {v!r} is not a bool")
    for key in ("probe_ms_on", "probe_ms_off", "speedup_vs_off",
                "hbm_gb_per_step_on", "hbm_gb_per_step_off"):
        v = cf.get(key)
        if v is not None and not _nonneg_num(v):
            problems.append(f"{where}.{key} {v!r} is not a non-negative "
                            f"number or null")
    pct = cf.get("hbm_pct_saved")
    if pct is not None and (not isinstance(pct, (int, float))
                            or isinstance(pct, bool) or pct != pct
                            or pct > 100.0):
        problems.append(f"{where}.hbm_pct_saved {pct!r} is not a number "
                        f"<= 100 or null")
    ks = cf.get("kernel_stats")
    if ks is not None:
        if not isinstance(ks, dict):
            problems.append(f"{where}.kernel_stats is not an object")
        else:
            for k, v in ks.items():
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    problems.append(f"{where}.kernel_stats[{k!r}] {v!r} is "
                                    f"not a non-negative integer")
    mab = cf.get("micro_ab")
    if mab is not None:
        if not isinstance(mab, dict):
            problems.append(f"{where}.micro_ab is not an object")
        else:
            for i, r in enumerate(mab.get("rows") or []):
                if not isinstance(r, dict):
                    problems.append(f"{where}.micro_ab.rows[{i}] is not "
                                    f"an object")
                    continue
                if not isinstance(r.get("shape"), str):
                    problems.append(f"{where}.micro_ab.rows[{i}].shape "
                                    f"{r.get('shape')!r} is not a string")
                for key in ("composed_gb_cost_analysis", "fused_gb_model"):
                    if not _nonneg_num(r.get(key)):
                        problems.append(
                            f"{where}.micro_ab.rows[{i}].{key} "
                            f"{r.get(key)!r} is not a non-negative number")
                ps = r.get("pct_saved")
                if not isinstance(ps, (int, float)) or isinstance(ps, bool)\
                        or ps != ps or ps > 100.0:
                    problems.append(f"{where}.micro_ab.rows[{i}].pct_saved "
                                    f"{ps!r} is not a number <= 100")
    return problems


def _validate_device_memory_metrics(where: str, metrics: dict) -> List[str]:
    """`device_memory_*` families must be gauges of non-negative values
    whose series carry the `device` label."""
    problems = []
    for name, fam in metrics.items():
        if not name.startswith("device_memory_"):
            continue
        if not isinstance(fam, dict) or fam.get("kind") != "gauge":
            problems.append(f"{where}.metrics.{name}: kind "
                            f"{fam.get('kind') if isinstance(fam, dict) else fam!r}"
                            f", expected gauge")
            continue
        for i, v in enumerate(fam.get("values") or []):
            if not isinstance(v, dict):
                problems.append(f"{where}.metrics.{name}[{i}] is not a "
                                f"series object")
                continue
            val = v.get("value")
            if not isinstance(val, (int, float)) or val < 0:
                problems.append(f"{where}.metrics.{name}[{i}]: value "
                                f"{val!r} is not a non-negative number")
            if "device" not in (v.get("labels") or {}):
                problems.append(f"{where}.metrics.{name}[{i}]: series "
                                f"missing the 'device' label")
    return problems


_AUDIT_SEVERITIES = ("info", "low", "medium", "high")
_AUDIT_CHECKS = ("donation", "dtype", "sharding", "bloat")


def _validate_program_audit(where: str, pa) -> List[str]:
    """A config's `program_audit` block: aggregate severity counts, a
    `clean_high` verdict consistent with them, and per-report findings
    whose check/severity are legal — the static auditor's bench
    contract. An `error` block (audit failed on this box) is legal but
    must name the error."""
    problems = []
    if not isinstance(pa, dict):
        return [f"{where}.program_audit is not an object"]
    if "error" in pa:
        if not isinstance(pa["error"], str) or not pa["error"]:
            problems.append(f"{where}.program_audit.error must be a "
                            f"non-empty string")
        return problems
    counts = pa.get("counts")
    if not isinstance(counts, dict):
        problems.append(f"{where}.program_audit.counts missing")
        counts = {}
    for sev in _AUDIT_SEVERITIES:
        v = counts.get(sev)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            problems.append(f"{where}.program_audit.counts.{sev}: "
                            f"{v!r} is not a non-negative int")
    ch = pa.get("clean_high")
    if not isinstance(ch, bool):
        problems.append(f"{where}.program_audit.clean_high must be a bool")
    elif isinstance(counts.get("high"), int) and \
            ch != (counts["high"] == 0):
        problems.append(f"{where}.program_audit.clean_high={ch} "
                        f"contradicts counts.high={counts['high']}")
    reports = pa.get("reports")
    if not isinstance(reports, list):
        problems.append(f"{where}.program_audit.reports is not a list")
        return problems
    for i, rep in enumerate(reports):
        if not isinstance(rep, dict):
            problems.append(f"{where}.program_audit.reports[{i}] is not "
                            f"an object")
            continue
        for key in ("name", "entry"):
            if not isinstance(rep.get(key), str) or not rep.get(key):
                problems.append(f"{where}.program_audit.reports[{i}]."
                                f"{key} must be a non-empty string")
        for j, f in enumerate(rep.get("findings") or []):
            loc = f"{where}.program_audit.reports[{i}].findings[{j}]"
            if not isinstance(f, dict):
                problems.append(f"{loc} is not an object")
                continue
            if f.get("check") not in _AUDIT_CHECKS:
                problems.append(f"{loc}.check {f.get('check')!r} not in "
                                f"{_AUDIT_CHECKS}")
            if f.get("severity") not in _AUDIT_SEVERITIES:
                problems.append(f"{loc}.severity {f.get('severity')!r} "
                                f"not in {_AUDIT_SEVERITIES}")
            for key in ("code", "message"):
                if not isinstance(f.get(key), str) or not f.get(key):
                    problems.append(f"{loc}.{key} must be a non-empty "
                                    f"string")
    return problems


# static-analysis metric families: name -> (kind, required labels)
_ANALYSIS_FAMILIES = {
    "analysis_findings_total": ("counter", ("check", "severity")),
    "analysis_audits_total": ("counter", ("entry",)),
}


def _validate_analysis_metrics(where: str, metrics: dict) -> List[str]:
    """`analysis_*` families must be counters with non-negative values,
    check/severity labels drawn from the auditor's legal sets, and a
    non-empty entry label."""
    problems = []
    for name, fam in metrics.items():
        if not name.startswith("analysis_"):
            continue
        spec = _ANALYSIS_FAMILIES.get(name)
        if spec is None:
            problems.append(f"{where}.metrics.{name}: unknown analysis "
                            f"family (expected one of "
                            f"{sorted(_ANALYSIS_FAMILIES)})")
            continue
        kind, req_labels = spec
        if not isinstance(fam, dict) or fam.get("kind") != kind:
            problems.append(
                f"{where}.metrics.{name}: kind "
                f"{fam.get('kind') if isinstance(fam, dict) else fam!r}, "
                f"expected {kind}")
            continue
        values = fam.get("values") or []
        if not isinstance(values, list):
            problems.append(f"{where}.metrics.{name}.values is not a list")
            continue
        for i, v in enumerate(values):
            if not isinstance(v, dict):
                problems.append(f"{where}.metrics.{name}[{i}] is not a "
                                f"series object")
                continue
            val = v.get("value")
            if not isinstance(val, (int, float)) or \
                    isinstance(val, bool) or val != val or val < 0:
                problems.append(f"{where}.metrics.{name}[{i}]: value "
                                f"{val!r} is not a non-negative number")
            labels = v.get("labels") or {}
            for lk in req_labels:
                if lk not in labels:
                    problems.append(f"{where}.metrics.{name}[{i}]: series "
                                    f"missing the {lk!r} label")
            if "severity" in labels and \
                    labels["severity"] not in _AUDIT_SEVERITIES:
                problems.append(f"{where}.metrics.{name}[{i}]: severity "
                                f"label {labels['severity']!r} not in "
                                f"{_AUDIT_SEVERITIES}")
            if "check" in labels and labels["check"] not in _AUDIT_CHECKS:
                problems.append(f"{where}.metrics.{name}[{i}]: check "
                                f"label {labels['check']!r} not in "
                                f"{_AUDIT_CHECKS}")
    return problems


def validate_observability(doc: dict) -> List[str]:
    """Schema problems in the document's observability sections (empty =
    valid). step_records must conform to the step-record contract,
    events/events_tail to the event contract (`controller_decision`
    events additionally to the decision contract: policy/action/legal
    outcome/decision id), `checkpoint_async_*` / `device_memory_*` /
    `health_*` / `amp_*` / `autotune_*` / `controller_*` / `disagg_*` /
    `serving_*` / `slo_*` / `analysis_*` metric families to their
    kind/label/shape
    contracts, `reqtrace`/`slo` observability blocks to the request-trace
    and SLO-window shapes (quantiles finite + monotone p50<=p95<=p99,
    breach counts non-negative),
    per-config `program_audit` blocks to the static-auditor contract
    (severity counts, clean_high verdict, legal check/severity per
    finding), `gpt2_decode`
    configs (a `serving`/`paged_vs_dense` block) to the decode-bench
    contract (TTFT/TPOT percentiles, goodput fields, A/B rows),
    `device_time` blocks to
    the per-op row shape with a known provenance label (estimate /
    measured / xplane), `health` blocks to the sentinel-overhead shape,
    and `autotune` blocks (per config and the observability summary) to
    the tuner's event/tuned-log shape; a missing section is fine (old
    rounds), a malformed one is not."""
    from paddle_tpu.profiler.events import validate_event
    from paddle_tpu.profiler.monitor import validate_step_record
    problems = []
    # per-config `autotune`/`profile`/`conv_fusion` blocks sit beside
    # (not inside) observability
    for name, cfg in (doc.get("configs") or {}).items():
        if not isinstance(cfg, dict):
            continue
        at = cfg.get("autotune")
        if at is not None:
            problems.extend(_validate_autotune_block(
                f"configs.{name}.autotune", at))
        prof = cfg.get("profile")
        if isinstance(prof, dict) and prof.get("segments") is not None:
            problems.extend(_validate_segments(
                f"configs.{name}.profile.segments", prof["segments"]))
        cf = cfg.get("conv_fusion")
        if cf is not None:
            problems.extend(_validate_conv_fusion(
                f"configs.{name}.conv_fusion", cf))
        if cfg.get("serving") is not None \
                or cfg.get("paged_vs_dense") is not None:
            problems.extend(_validate_decode_block(f"configs.{name}", cfg))
        pa = cfg.get("program_audit")
        if pa is not None:
            problems.extend(_validate_program_audit(f"configs.{name}", pa))
    for where, obs in _obs_blocks(doc):
        metrics = obs.get("metrics")
        if isinstance(metrics, dict):
            problems.extend(_validate_async_ckpt_metrics(where, metrics))
            problems.extend(_validate_device_memory_metrics(where, metrics))
            problems.extend(_validate_health_metrics(where, metrics))
            problems.extend(_validate_autotune_metrics(where, metrics))
            problems.extend(_validate_controller_metrics(where, metrics))
            problems.extend(_validate_disagg_metrics(where, metrics))
            problems.extend(_validate_serving_metrics(where, metrics))
            problems.extend(_validate_slo_metrics(where, metrics))
            problems.extend(_validate_analysis_metrics(where, metrics))
        rt = obs.get("reqtrace")
        if rt is not None:
            problems.extend(_validate_reqtrace_block(f"{where}.reqtrace",
                                                     rt))
        slo_blk = obs.get("slo")
        if slo_blk is not None:
            problems.extend(_validate_slo_block(f"{where}.slo", slo_blk))
        at = obs.get("autotune")
        if at is not None:
            problems.extend(_validate_autotune_block(f"{where}.autotune",
                                                     at))
        dt = obs.get("device_time")
        if dt is not None:
            problems.extend(_validate_device_time(where, dt))
        h = obs.get("health")
        if h is not None:
            problems.extend(_validate_health_block(where, h))
        recs = obs.get("step_records")
        if recs is not None:
            if not isinstance(recs, list):
                problems.append(f"{where}.step_records is not a list")
            else:
                for i, rec in enumerate(recs):
                    try:
                        validate_step_record(rec)
                    except ValueError as e:
                        problems.append(f"{where}.step_records[{i}]: {e}")
        for key in ("events_tail", "events"):
            evs = obs.get(key)
            if evs is None:
                continue
            if not isinstance(evs, list):
                problems.append(f"{where}.{key} is not a list")
                continue
            for i, ev in enumerate(evs):
                try:
                    validate_event(ev)
                except ValueError as e:
                    problems.append(f"{where}.{key}[{i}]: {e}")
                    continue
                if isinstance(ev, dict) \
                        and ev.get("kind") == "controller_decision":
                    problems.extend(_validate_controller_decision(
                        f"{where}.{key}[{i}]", ev))
    return problems


def format_rows(rows) -> str:
    lines = [f"{'config':<24} {'metric':<22} {'baseline':>12} "
             f"{'current':>12} {'change':>8}  status"]
    for name, metric, b, c, rel, status in rows:
        bs = f"{b:,.1f}" if b is not None else "-"
        cs = f"{c:,.1f}" if c is not None else "-"
        rs = f"{100 * rel:+.1f}%" if rel is not None else "-"
        lines.append(f"{name:<24} {metric:<22} {bs:>12} {cs:>12} {rs:>8}  "
                     f"{status}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", required=True)
    ap.add_argument("--current", required=True)
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative drop that fails the gate (default 5%%)")
    ap.add_argument("--no-obs-check", action="store_true",
                    help="skip observability schema validation of the "
                         "current round")
    ap.add_argument("--assume-baseline-platform", default=None,
                    metavar="PLAT",
                    help="platform the baseline round ran on when its "
                         "file predates per-config platform fields "
                         "(r01-r05 driver rounds ran on the TPU box: "
                         "pass 'tpu'); configs whose declared platforms "
                         "differ are reported 'incomparable' instead of "
                         "gated")
    args = ap.parse_args(argv)
    try:
        current = _load(args.current)
        rows = compare(_load(args.baseline), current, args.threshold,
                       baseline_platform=args.assume_baseline_platform)
    except (OSError, ValueError) as e:
        print(f"check_bench_result: {e}", file=sys.stderr)
        return 2
    print(format_rows(rows))
    obs_problems = [] if args.no_obs_check else validate_observability(current)
    bad = [r for r in rows if r[5] in ("regressed", "missing")]
    if obs_problems:
        print(f"\nobservability schema violations in {args.current}:")
        for p in obs_problems:
            print(f"  - {p}")
    if bad or obs_problems:
        msgs = []
        if bad:
            msgs.append(f"{len(bad)} config(s) regressed or missing "
                        f"(threshold {100 * args.threshold:.0f}%)")
        if obs_problems:
            msgs.append(f"{len(obs_problems)} observability schema "
                        f"violation(s)")
        print(f"\nFAIL: " + "; ".join(msgs))
        return 1
    print("\nOK: no regressions")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
